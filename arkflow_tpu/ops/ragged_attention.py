"""Ragged flash attention: per-row sequence lengths, no wasted tiles.

The streaming engine pads variable-length batches to a bucket; plain attention
then burns MXU cycles on padding. This kernel (the ragged-attention pattern of
PAPERS.md "Ragged Paged Attention") takes the true ``lengths`` per row as a
scalar-prefetch argument and bounds the K/V tile loop per (batch, q-tile)
program at the row's real length — fully-padded tiles are never touched, and
padded key positions inside the last tile are masked. Output rows beyond a
row's length are zeros.

Same VMEM/online-softmax structure as ``flash_attention``; use it when batches
are bucketed well above their typical fill.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG = -1e30


def flash_softmax_loop(q, k_ref, v_ref, n_tiles, tile_k: int, valid_at):
    """The online-softmax accumulation over K tiles shared by the ragged and
    segment kernels (ops/segment_attention.py) — ONE copy of the numerically
    delicate m/l/corr recurrence. ``valid_at(t) -> [TQ, TK] bool`` supplies
    each kernel's masking rule. Returns (o, m, l) after ``n_tiles`` tiles.
    """
    tq, d = q.shape
    scale = 1.0 / math.sqrt(d)

    def body(t, carry):
        o, m, l = carry
        k = k_ref[0, 0, pl.ds(t * tile_k, tile_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(t * tile_k, tile_k), :].astype(jnp.float32)
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        scores = jnp.where(valid_at(t), scores, _NEG)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        p = jnp.exp(scores - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((tq, d), jnp.float32)
    m0 = jnp.full((tq,), _NEG, jnp.float32)
    l0 = jnp.zeros((tq,), jnp.float32)
    return jax.lax.fori_loop(0, n_tiles, body, (o0, m0, l0))


def _ragged_kernel(lengths_ref, q_ref, k_ref, v_ref, o_ref, *, tile_k: int, causal: bool):
    bi = pl.program_id(0)
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)  # [TQ, D]
    tq, d = q.shape
    s = k_ref.shape[2]
    length = lengths_ref[bi]

    # K tiles that contain any valid key for this row
    n_k_row = (length + tile_k - 1) // tile_k
    if causal:
        n_k_causal = ((qi + 1) * tq + tile_k - 1) // tile_k
        n_k_row = jnp.minimum(n_k_row, n_k_causal)
    n_k_row = jnp.minimum(n_k_row, s // tile_k)

    q_pos = qi * tq + jax.lax.broadcasted_iota(jnp.int32, (tq, tile_k), 0)

    def valid_at(t):
        k_pos = t * tile_k + jax.lax.broadcasted_iota(jnp.int32, (tq, tile_k), 1)
        # mask padded keys AND padded queries (pad-query rows emit zeros)
        valid = jnp.logical_and(k_pos < length, q_pos < length)
        if causal:
            valid = jnp.logical_and(valid, k_pos <= q_pos)
        return valid

    o, m, l = flash_softmax_loop(q, k_ref, v_ref, n_k_row, tile_k, valid_at)
    # pad queries (beyond the row's true length) emit zeros; note a fully
    # masked softmax degenerates to uniform (exp(NEG-NEG)=1), so masking by
    # the accumulator alone is not sufficient — mask by query position.
    q_valid = (qi * tq + jax.lax.broadcasted_iota(jnp.int32, (tq, 1), 0)) < length
    o_ref[0, 0] = jnp.where(
        q_valid, o / jnp.maximum(l[:, None], 1e-30), 0.0
    ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "tile_q", "tile_k", "interpret"))
def ragged_flash_attention(q, k, v, lengths, *, causal: bool = False,
                           tile_q: int = 128, tile_k: int = 128,
                           interpret: bool = False):
    """q/k/v: [B, H, S, D]; lengths: [B] int32 true sequence lengths."""
    b, h, s, d = q.shape
    tile_q = min(tile_q, s)
    tile_k = min(tile_k, s)
    if s % tile_q or s % tile_k:
        raise ValueError(f"seq len {s} must divide tiles ({tile_q}, {tile_k})")
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401 (memory spaces default)

    grid = (b, h, s // tile_q)
    kernel = functools.partial(_ragged_kernel, tile_k=tile_k, causal=causal)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, tile_q, d), lambda bi, hi, qi, *_: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi, *_: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi, *_: (bi, hi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, tile_q, d), lambda bi, hi, qi, *_: (bi, hi, qi, 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(jnp.asarray(lengths, jnp.int32), q, k, v)


# -- paged flash attention ---------------------------------------------------
#
# The decode-time twin of the ragged kernel: K/V live in the serving page
# pools ([num_pages, page, kv_heads, dh], tpu/serving.py), and each row's
# context is named by an int32 page table instead of being contiguous. The
# dense-gather path in models/paged_decode.py materializes kp[page_table]
# — a [B, P*page, heads, dh] copy of the whole context per layer per step —
# then runs masked XLA attention over it. This kernel reads the page table
# in place ("Ragged Paged Attention", PAPERS.md): the grid walks
# (row, kv_head, page), the BlockSpec index map resolves each row's p-th
# page through the scalar-prefetched table, and pages past the row's causal
# bound resolve to the scratch page 0 so consecutive out-of-range steps
# reuse one block copy and skip the math. GQA is folded into the query
# tile: the ``group`` query heads sharing a KV head ride one [C*group, dh]
# tile, so K/V are never repeated ``group``-fold in HBM or VMEM.


def _paged_kernel(off_ref, table_ref, q_ref, k_ref, v_ref, o_ref,
                  o_acc, m_acc, l_acc, *, page: int, group: int,
                  pages_per: int):
    bi = pl.program_id(0)
    pi = pl.program_id(2)
    ql, d = q_ref.shape[2], q_ref.shape[3]

    @pl.when(pi == 0)
    def _init():
        o_acc[:] = jnp.zeros_like(o_acc)
        m_acc[:] = jnp.full_like(m_acc, _NEG)
        l_acc[:] = jnp.zeros_like(l_acc)

    off = off_ref[bi]
    # folded query i is (chunk position i // group, q head i % group) at
    # absolute position off + i//group; the row's last attendable key is
    # off + C - 1, so later pages hold no admissible key for any query
    max_pos = off + (ql // group - 1)

    @pl.when(pi * page <= max_pos)
    def _acc():
        q = q_ref[0, 0].astype(jnp.float32)                       # [QL, D]
        k = k_ref[0, :, 0, :].astype(jnp.float32)                 # [page, D]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        scale = 1.0 / math.sqrt(d)
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale           # [QL, page]
        q_pos = off + jax.lax.broadcasted_iota(jnp.int32, (ql, page), 0) // group
        k_pos = pi * page + jax.lax.broadcasted_iota(jnp.int32, (ql, page), 1)
        scores = jnp.where(k_pos <= q_pos, scores, _NEG)
        m = m_acc[:, :1]                                          # [QL, 1]
        m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        corr = jnp.exp(m - m_new)
        l_acc[:, :1] = l_acc[:, :1] * corr + p.sum(axis=-1, keepdims=True)
        m_acc[:, :1] = m_new
        o_acc[:] = o_acc[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(pi == pages_per - 1)
    def _fin():
        # every query admits at least key 0 (k_pos=0 <= q_pos always), so l
        # is never truly zero; the floor only guards numerical underflow
        o_ref[0, 0] = (o_acc[:] / jnp.maximum(l_acc[:, :1], 1e-30)
                       ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_flash_attention(q, k_pages, v_pages, page_table, off, *,
                          interpret: bool = False):
    """Flash attention that reads K/V straight from the serving page pools.

    q: [B, C, H, dh] — C queries per row at absolute positions
    ``off[b] + i`` (decode: C=1, off=lengths; chunked prefill: off=chunk
    offset). k_pages/v_pages: [num_pages, page, kv_heads, dh] (one layer's
    pool slice). page_table: [B, P] int32 — entries past a row's context
    may be 0 (the scratch page; never read through the causal mask).
    off: [B] int32.

    Query i attends keys 0..off+i — exactly the dense-gather reference's
    ``key_pos <= positions`` mask — with GQA resolved inside the kernel
    (no ``jnp.repeat`` of K/V). Returns [B, C, H, dh] in q's dtype.
    """
    b, c, h, dh = q.shape
    n_pages, page, kvh, _ = k_pages.shape
    if h % kvh:
        raise ValueError(f"q heads {h} must be a multiple of kv heads {kvh}")
    group = h // kvh
    ql = c * group
    pages_per = page_table.shape[1]
    # fold the GQA group into the query tile: [B, KVH, C*G, dh] where folded
    # index i = (chunk pos i//G, group member i%G) — all G members share the
    # same KV head and the same absolute position
    qf = (q.reshape(b, c, kvh, group, dh)
          .transpose(0, 2, 1, 3, 4)
          .reshape(b, kvh, ql, dh))
    from jax.experimental.pallas import tpu as pltpu

    grid = (b, kvh, pages_per)
    kernel = functools.partial(
        _paged_kernel, page=page, group=group, pages_per=pages_per)

    def _page_index(bi, hi, pi, off_ref, table_ref):
        # pages past the row's causal bound resolve to the scratch page 0:
        # the index stays constant across the remaining grid steps, so the
        # pipeline skips the re-copy, and pl.when skips the math
        max_pos = off_ref[bi] + (ql // group - 1)
        return (jnp.where(pi * page <= max_pos, table_ref[bi, pi], 0), 0, hi, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, ql, dh), lambda bi, hi, pi, *_: (bi, hi, 0, 0)),
            pl.BlockSpec((1, page, 1, dh), _page_index),
            pl.BlockSpec((1, page, 1, dh), _page_index),
        ],
        out_specs=pl.BlockSpec((1, 1, ql, dh),
                               lambda bi, hi, pi, *_: (bi, hi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((ql, dh), jnp.float32),
            pltpu.VMEM((ql, 128), jnp.float32),
            pltpu.VMEM((ql, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh, ql, dh), q.dtype),
        interpret=interpret,
    )(jnp.asarray(off, jnp.int32), jnp.asarray(page_table, jnp.int32),
      qf, k_pages, v_pages)
    return (out.reshape(b, kvh, c, group, dh)
            .transpose(0, 2, 1, 3, 4)
            .reshape(b, c, h, dh))
