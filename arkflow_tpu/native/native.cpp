// Native host-runtime tier.
//
// The reference's entire engine is native (Rust); here the JAX/XLA path owns
// device compute and this library owns the host-side hot loops that pure
// Python would bottleneck:
//   - crc32c (Castagnoli): Kafka record-batch checksums (slice-by-8).
//   - hash tokenizer: batch text -> (ids, mask) for streaming token models;
//     semantics identical to the Python fallback in arkflow_tpu/tpu/tokenizer.py
//     (lowercase, [a-z0-9]+ runs or single symbol, FNV-1a 32-bit into [4, vocab)).
//   - micro-batch assembler: gather+pad variable-length int32 rows into a
//     fixed [batch, seq] bucket (the pad-to-bucket step of the TPU infeed).
//
// Built by arkflow_tpu/native/__init__.py with g++ -O3 -shared -fPIC; every
// entry point has a Python fallback, so the engine still runs if no compiler
// is present.

#include <cstdint>
#include <cstring>
#include <cstddef>

extern "C" {

// ---------------------------------------------------------------------------
// crc32c, slice-by-8
// ---------------------------------------------------------------------------

static uint32_t crc32c_table[8][256];
static bool crc32c_init_done = false;

static void crc32c_init() {
    const uint32_t poly = 0x82f63b78u;  // reflected Castagnoli
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t crc = i;
        for (int j = 0; j < 8; j++)
            crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
        crc32c_table[0][i] = crc;
    }
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t crc = crc32c_table[0][i];
        for (int s = 1; s < 8; s++) {
            crc = crc32c_table[0][crc & 0xff] ^ (crc >> 8);
            crc32c_table[s][i] = crc;
        }
    }
    crc32c_init_done = true;
}

uint32_t ark_crc32c(const uint8_t* data, size_t len, uint32_t crc) {
    if (!crc32c_init_done) crc32c_init();
    crc = ~crc;
    while (len >= 8) {
        uint64_t word;
        memcpy(&word, data, 8);
        word ^= crc;  // little-endian assumed (x86/arm64)
        crc = crc32c_table[7][word & 0xff] ^
              crc32c_table[6][(word >> 8) & 0xff] ^
              crc32c_table[5][(word >> 16) & 0xff] ^
              crc32c_table[4][(word >> 24) & 0xff] ^
              crc32c_table[3][(word >> 32) & 0xff] ^
              crc32c_table[2][(word >> 40) & 0xff] ^
              crc32c_table[1][(word >> 48) & 0xff] ^
              crc32c_table[0][(word >> 56) & 0xff];
        data += 8;
        len -= 8;
    }
    while (len--) crc = crc32c_table[0][(crc ^ *data++) & 0xff] ^ (crc >> 8);
    return ~crc;
}

// ---------------------------------------------------------------------------
// hash tokenizer (must match tpu/tokenizer.py HashTokenizer exactly)
// ---------------------------------------------------------------------------

static inline uint32_t fnv1a32(const uint8_t* s, size_t n) {
    uint32_t h = 2166136261u;
    for (size_t i = 0; i < n; i++) {
        h ^= s[i];
        h *= 16777619u;
    }
    return h;
}

static inline bool is_alnum_ascii(uint8_t c) {
    return (c >= 'a' && c <= 'z') || (c >= '0' && c <= '9');
}

// texts: concatenated lowercased-on-the-fly bytes; offsets[n+1] delimit texts.
// Writes ids[n][max_len], mask[n][max_len] (row-major, pre-zeroed by caller).
void ark_hash_tokenize(const uint8_t* buf, const int64_t* offsets, int n_texts,
                       int max_len, int vocab_size, int32_t* ids, int32_t* mask) {
    const int32_t CLS = 1, SEP = 2;
    const int body = max_len - 2;
    for (int t = 0; t < n_texts; t++) {
        int32_t* row_ids = ids + (size_t)t * max_len;
        int32_t* row_mask = mask + (size_t)t * max_len;
        row_ids[0] = CLS;
        int count = 0;  // tokens emitted (excluding cls/sep)
        const uint8_t* p = buf + offsets[t];
        const uint8_t* end = buf + offsets[t + 1];
        while (p < end && count < body) {
            uint8_t c = *p;
            if (c >= 'A' && c <= 'Z') c += 32;
            if (c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f' || c == '\v') {
                p++;
                continue;
            }
            // stream the FNV hash over the token — unbounded length, exactly
            // like the Python regex path hashing the whole run
            uint32_t h = 2166136261u;
            if (is_alnum_ascii(c)) {
                while (p < end) {
                    uint8_t d = *p;
                    if (d >= 'A' && d <= 'Z') d += 32;
                    if (!is_alnum_ascii(d)) break;
                    h = (h ^ d) * 16777619u;
                    p++;
                }
            } else {
                h = (h ^ c) * 16777619u;
                p++;
            }
            row_ids[1 + count] = 4 + (int32_t)(h % (uint32_t)(vocab_size - 4));
            count++;
        }
        row_ids[1 + count] = SEP;
        for (int i = 0; i < count + 2; i++) row_mask[i] = 1;
    }
}

// ---------------------------------------------------------------------------
// micro-batch assembler: ragged int32 rows -> padded [batch, seq] bucket
// ---------------------------------------------------------------------------

// values: concatenated row values; offsets[n+1]; out: pre-zeroed [bucket_rows, seq].
void ark_pad_gather_i32(const int32_t* values, const int64_t* offsets, int n_rows,
                        int seq, int32_t* out) {
    for (int r = 0; r < n_rows; r++) {
        int64_t lo = offsets[r], hi = offsets[r + 1];
        int64_t n = hi - lo;
        if (n > seq) n = seq;
        memcpy(out + (size_t)r * seq, values + lo, (size_t)n * sizeof(int32_t));
    }
}

}  // extern "C"
