// Native host-runtime tier.
//
// The reference's entire engine is native (Rust); here the JAX/XLA path owns
// device compute and this library owns the host-side hot loops that pure
// Python would bottleneck:
//   - crc32c (Castagnoli): Kafka record-batch checksums (slice-by-8).
//   - hash tokenizer: batch text -> (ids, mask) for streaming token models;
//     semantics identical to the Python fallback in arkflow_tpu/tpu/tokenizer.py
//     (lowercase, [a-z0-9]+ runs or single symbol, FNV-1a 32-bit into [4, vocab)).
//   - micro-batch assembler: gather+pad variable-length int32 rows into a
//     fixed [batch, seq] bucket (the pad-to-bucket step of the TPU infeed).
//   - token packer: first-fit-decreasing bin pack of ragged examples into
//     dense model rows (padding-free execution; tpu/packing.py layout).
//
// Built by arkflow_tpu/native/__init__.py with g++ -O3 -shared -fPIC; every
// entry point has a Python fallback, so the engine still runs if no compiler
// is present.

#include <cstdint>
#include <cstring>
#include <cstddef>
#include <algorithm>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// crc32c, slice-by-8
// ---------------------------------------------------------------------------

static uint32_t crc32c_table[8][256];
static bool crc32c_init_done = false;

static void crc32c_init() {
    const uint32_t poly = 0x82f63b78u;  // reflected Castagnoli
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t crc = i;
        for (int j = 0; j < 8; j++)
            crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
        crc32c_table[0][i] = crc;
    }
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t crc = crc32c_table[0][i];
        for (int s = 1; s < 8; s++) {
            crc = crc32c_table[0][crc & 0xff] ^ (crc >> 8);
            crc32c_table[s][i] = crc;
        }
    }
    crc32c_init_done = true;
}

uint32_t ark_crc32c(const uint8_t* data, size_t len, uint32_t crc) {
    if (!crc32c_init_done) crc32c_init();
    crc = ~crc;
    while (len >= 8) {
        uint64_t word;
        memcpy(&word, data, 8);
        word ^= crc;  // little-endian assumed (x86/arm64)
        crc = crc32c_table[7][word & 0xff] ^
              crc32c_table[6][(word >> 8) & 0xff] ^
              crc32c_table[5][(word >> 16) & 0xff] ^
              crc32c_table[4][(word >> 24) & 0xff] ^
              crc32c_table[3][(word >> 32) & 0xff] ^
              crc32c_table[2][(word >> 40) & 0xff] ^
              crc32c_table[1][(word >> 48) & 0xff] ^
              crc32c_table[0][(word >> 56) & 0xff];
        data += 8;
        len -= 8;
    }
    while (len--) crc = crc32c_table[0][(crc ^ *data++) & 0xff] ^ (crc >> 8);
    return ~crc;
}

// ---------------------------------------------------------------------------
// hash tokenizer (must match tpu/tokenizer.py HashTokenizer exactly)
// ---------------------------------------------------------------------------

static inline uint32_t fnv1a32(const uint8_t* s, size_t n) {
    uint32_t h = 2166136261u;
    for (size_t i = 0; i < n; i++) {
        h ^= s[i];
        h *= 16777619u;
    }
    return h;
}

static inline bool is_alnum_ascii(uint8_t c) {
    return (c >= 'a' && c <= 'z') || (c >= '0' && c <= '9');
}

// texts: concatenated lowercased-on-the-fly bytes; offsets[n+1] delimit texts.
// Writes ids[n][max_len], mask[n][max_len] (row-major, pre-zeroed by caller).
void ark_hash_tokenize(const uint8_t* buf, const int64_t* offsets, int n_texts,
                       int max_len, int vocab_size, int32_t* ids, int32_t* mask) {
    const int32_t CLS = 1, SEP = 2;
    const int body = max_len - 2;
    for (int t = 0; t < n_texts; t++) {
        int32_t* row_ids = ids + (size_t)t * max_len;
        int32_t* row_mask = mask + (size_t)t * max_len;
        row_ids[0] = CLS;
        int count = 0;  // tokens emitted (excluding cls/sep)
        const uint8_t* p = buf + offsets[t];
        const uint8_t* end = buf + offsets[t + 1];
        while (p < end && count < body) {
            uint8_t c = *p;
            if (c >= 'A' && c <= 'Z') c += 32;
            if (c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f' || c == '\v') {
                p++;
                continue;
            }
            // stream the FNV hash over the token — unbounded length, exactly
            // like the Python regex path hashing the whole run
            uint32_t h = 2166136261u;
            if (is_alnum_ascii(c)) {
                while (p < end) {
                    uint8_t d = *p;
                    if (d >= 'A' && d <= 'Z') d += 32;
                    if (!is_alnum_ascii(d)) break;
                    h = (h ^ d) * 16777619u;
                    p++;
                }
            } else {
                h = (h ^ c) * 16777619u;
                p++;
            }
            row_ids[1 + count] = 4 + (int32_t)(h % (uint32_t)(vocab_size - 4));
            count++;
        }
        row_ids[1 + count] = SEP;
        for (int i = 0; i < count + 2; i++) row_mask[i] = 1;
    }
}

// ---------------------------------------------------------------------------
// micro-batch assembler: ragged int32 rows -> padded [batch, seq] bucket
// ---------------------------------------------------------------------------

// values: concatenated row values; offsets[n+1]; out: pre-zeroed [bucket_rows, seq].
void ark_pad_gather_i32(const int32_t* values, const int64_t* offsets, int n_rows,
                        int seq, int32_t* out) {
    for (int r = 0; r < n_rows; r++) {
        int64_t lo = offsets[r], hi = offsets[r + 1];
        int64_t n = hi - lo;
        if (n > seq) n = seq;
        memcpy(out + (size_t)r * seq, values + lo, (size_t)n * sizeof(int32_t));
    }
}

// ---------------------------------------------------------------------------
// token packer: first-fit-decreasing bin pack for padding-free execution
// (tpu/packing.py owns the reference Python implementation + the layout
// contract; this is the hot-path tier — the Python FFD loop costs ~7ms per
// 1024-example batch on the 1-core bench host, this runs in microseconds)
// ---------------------------------------------------------------------------

// Phase 1: placement. lengths[n] (pre-clamped to [1, seq] by the caller);
// writes bin_of[n], start_of[n]; returns the bin count.
int ark_pack_ffd(const int64_t* lengths, int n, int seq,
                 int64_t* bin_of, int64_t* start_of) {
    std::vector<int> order(n);
    for (int i = 0; i < n; i++) order[i] = i;
    std::stable_sort(order.begin(), order.end(),
                     [&](int a, int b) { return lengths[a] > lengths[b]; });
    std::vector<int64_t> bin_free;
    bin_free.reserve(n);
    for (int k = 0; k < n; k++) {
        int i = order[k];
        int64_t len = lengths[i];
        int b = -1;
        for (size_t j = 0; j < bin_free.size(); j++) {  // first fit
            if (bin_free[j] >= len) { b = (int)j; break; }
        }
        if (b < 0) {
            b = (int)bin_free.size();
            bin_free.push_back(seq);
        }
        bin_of[i] = b;
        start_of[i] = seq - bin_free[b];
        bin_free[b] -= len;
    }
    return (int)bin_free.size();
}

// Phase 2: fill. ids row-major [n, smax]; out arrays pre-zeroed
// [n_bins, seq]; seg ids count up per bin in original example order.
void ark_pack_fill(const int32_t* ids, int64_t smax, const int64_t* lengths,
                   const int64_t* bin_of, const int64_t* start_of, int n,
                   int seq, int n_bins, int32_t* out_ids, int32_t* seg,
                   int32_t* pos, int32_t* ex_row, int32_t* ex_pos) {
    std::vector<int32_t> seg_next(n_bins, 1);
    for (int i = 0; i < n; i++) {
        int64_t b = bin_of[i], st = start_of[i], len = lengths[i];
        if (len > smax) len = smax;  // never read past the ids row
        int32_t* orow = out_ids + (size_t)b * seq + st;
        int32_t* srow = seg + (size_t)b * seq + st;
        int32_t* prow = pos + (size_t)b * seq + st;
        memcpy(orow, ids + (size_t)i * smax, (size_t)len * sizeof(int32_t));
        int32_t s = seg_next[b]++;
        for (int64_t j = 0; j < len; j++) { srow[j] = s; prow[j] = (int32_t)j; }
        ex_row[i] = (int32_t)b;
        ex_pos[i] = (int32_t)st;
    }
}

// ---------------------------------------------------------------------------
// xxHash32 (XXH32): LZ4-frame header/content checksums (Kafka codec 3)
// ---------------------------------------------------------------------------

static inline uint32_t xxh_rotl32(uint32_t x, int r) { return (x << r) | (x >> (32 - r)); }

uint32_t ark_xxh32(const uint8_t* p, size_t len, uint32_t seed) {
    const uint32_t P1 = 2654435761u, P2 = 2246822519u, P3 = 3266489917u,
                   P4 = 668265263u, P5 = 374761393u;
    const uint8_t* end = p + len;
    uint32_t h;
    if (len >= 16) {
        uint32_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed, v4 = seed - P1;
        const uint8_t* limit = end - 16;
        do {
            uint32_t w;
            memcpy(&w, p, 4); v1 = xxh_rotl32(v1 + w * P2, 13) * P1; p += 4;
            memcpy(&w, p, 4); v2 = xxh_rotl32(v2 + w * P2, 13) * P1; p += 4;
            memcpy(&w, p, 4); v3 = xxh_rotl32(v3 + w * P2, 13) * P1; p += 4;
            memcpy(&w, p, 4); v4 = xxh_rotl32(v4 + w * P2, 13) * P1; p += 4;
        } while (p <= limit);
        h = xxh_rotl32(v1, 1) + xxh_rotl32(v2, 7) + xxh_rotl32(v3, 12) + xxh_rotl32(v4, 18);
    } else {
        h = seed + P5;
    }
    h += (uint32_t)len;
    while (p + 4 <= end) {
        uint32_t w;
        memcpy(&w, p, 4);
        h = xxh_rotl32(h + w * P3, 17) * P4;
        p += 4;
    }
    while (p < end) h = xxh_rotl32(h + (*p++) * P5, 11) * P1;
    h ^= h >> 15; h *= P2; h ^= h >> 13; h *= P3; h ^= h >> 16;
    return h;
}

// ---------------------------------------------------------------------------
// LZ4 block codec (Kafka codec 3 rides the LZ4 *frame* format; the Python
// layer owns framing, these own the block byte machine)
// ---------------------------------------------------------------------------

int64_t ark_lz4_decompress_block(const uint8_t* src, size_t srclen,
                                 uint8_t* dst, size_t dstcap) {
    const uint8_t* ip = src;
    const uint8_t* iend = src + srclen;
    uint8_t* op = dst;
    uint8_t* oend = dst + dstcap;
    while (ip < iend) {
        uint8_t token = *ip++;
        size_t litlen = token >> 4;
        if (litlen == 15) {
            uint8_t b;
            do { if (ip >= iend) return -1; b = *ip++; litlen += b; } while (b == 255);
        }
        if ((size_t)(iend - ip) < litlen || (size_t)(oend - op) < litlen) return -1;
        memcpy(op, ip, litlen);
        ip += litlen; op += litlen;
        if (ip >= iend) break;  // block ends with literals
        if (iend - ip < 2) return -1;
        uint32_t offset = ip[0] | ((uint32_t)ip[1] << 8);
        ip += 2;
        if (offset == 0 || (size_t)(op - dst) < offset) return -1;
        size_t mlen = token & 15;
        if (mlen == 15) {
            uint8_t b;
            do { if (ip >= iend) return -1; b = *ip++; mlen += b; } while (b == 255);
        }
        mlen += 4;
        if ((size_t)(oend - op) < mlen) return -1;
        const uint8_t* match = op - offset;
        while (mlen--) *op++ = *match++;  // byte-wise: overlap semantics
    }
    return op - dst;
}

static inline uint32_t lz4_hash(uint32_t v) { return (v * 2654435761u) >> 19; }  // 13-bit

// Greedy single-pass compressor (hash-chain-free, librdkafka-class ratio).
int64_t ark_lz4_compress_block(const uint8_t* src, size_t n,
                               uint8_t* dst, size_t cap) {
    uint8_t* op = dst;
    uint8_t* oend = dst + cap;
    const uint8_t* ip = src;
    const uint8_t* iend = src + n;
    const uint8_t* anchor = src;
    static thread_local int32_t table[1 << 13];
    for (size_t i = 0; i < (1 << 13); i++) table[i] = -1;

    if (n >= 13) {  // spec: last match starts >=12 bytes before end
        const uint8_t* mflimit = iend - 12;
        const uint8_t* matchlimit = iend - 5;  // last 5 bytes stay literals
        while (ip < mflimit) {
            uint32_t seq;
            memcpy(&seq, ip, 4);
            uint32_t h = lz4_hash(seq);
            int32_t cand = table[h];
            table[h] = (int32_t)(ip - src);
            uint32_t cseq = 0;
            if (cand < 0 || (size_t)((ip - src) - cand) > 65535) { ip++; continue; }
            memcpy(&cseq, src + cand, 4);
            if (cseq != seq) { ip++; continue; }
            const uint8_t* match = src + cand;
            size_t mlen = 4;
            while (ip + mlen < matchlimit && ip[mlen] == match[mlen]) mlen++;
            size_t litlen = (size_t)(ip - anchor);
            // worst-case emission size check
            if ((size_t)(oend - op) < 1 + litlen / 255 + 1 + litlen + 2 + mlen / 255 + 1)
                return -1;
            uint8_t* token = op++;
            if (litlen >= 15) {
                *token = 15 << 4;
                size_t rest = litlen - 15;
                while (rest >= 255) { *op++ = 255; rest -= 255; }
                *op++ = (uint8_t)rest;
            } else {
                *token = (uint8_t)(litlen << 4);
            }
            memcpy(op, anchor, litlen);
            op += litlen;
            uint32_t offset = (uint32_t)(ip - match);
            *op++ = (uint8_t)offset;
            *op++ = (uint8_t)(offset >> 8);
            size_t mrest = mlen - 4;
            if (mrest >= 15) {
                *token |= 15;
                mrest -= 15;
                while (mrest >= 255) { *op++ = 255; mrest -= 255; }
                *op++ = (uint8_t)mrest;
            } else {
                *token |= (uint8_t)mrest;
            }
            ip += mlen;
            anchor = ip;
        }
    }
    // trailing literals
    size_t litlen = (size_t)(iend - anchor);
    if ((size_t)(oend - op) < 1 + litlen / 255 + 1 + litlen) return -1;
    if (litlen >= 15) {
        *op++ = 15 << 4;
        size_t rest = litlen - 15;
        while (rest >= 255) { *op++ = 255; rest -= 255; }
        *op++ = (uint8_t)rest;
    } else {
        *op++ = (uint8_t)(litlen << 4);
    }
    memcpy(op, anchor, litlen);
    op += litlen;
    return op - dst;
}

// ---------------------------------------------------------------------------
// Snappy block codec (Kafka codec 2; Python layer handles xerial framing)
// ---------------------------------------------------------------------------

int64_t ark_snappy_decompress(const uint8_t* src, size_t srclen,
                              uint8_t* dst, size_t dstcap) {
    const uint8_t* ip = src;
    const uint8_t* iend = src + srclen;
    uint64_t ulen = 0;
    int shift = 0;
    for (;;) {
        if (ip >= iend || shift > 35) return -1;
        uint8_t b = *ip++;
        ulen |= (uint64_t)(b & 0x7f) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
    }
    if (ulen > dstcap) return -1;
    uint8_t* op = dst;
    uint8_t* oend = dst + ulen;
    while (ip < iend) {
        uint8_t tag = *ip++;
        uint32_t type = tag & 3;
        if (type == 0) {  // literal
            uint32_t len = (tag >> 2) + 1;
            if (len > 60) {
                uint32_t nb = len - 60;
                if ((size_t)(iend - ip) < nb) return -1;
                len = 0;
                for (uint32_t i = 0; i < nb; i++) len |= (uint32_t)ip[i] << (8 * i);
                ip += nb;
                len += 1;
            }
            if ((size_t)(iend - ip) < len || (size_t)(oend - op) < len) return -1;
            memcpy(op, ip, len);
            ip += len; op += len;
        } else {
            uint32_t len, offset;
            if (type == 1) {
                len = 4 + ((tag >> 2) & 7);
                if (ip >= iend) return -1;
                offset = ((uint32_t)(tag >> 5) << 8) | *ip++;
            } else if (type == 2) {
                len = (tag >> 2) + 1;
                if (iend - ip < 2) return -1;
                offset = ip[0] | ((uint32_t)ip[1] << 8);
                ip += 2;
            } else {
                len = (tag >> 2) + 1;
                if (iend - ip < 4) return -1;
                offset = ip[0] | ((uint32_t)ip[1] << 8) | ((uint32_t)ip[2] << 16) |
                         ((uint32_t)ip[3] << 24);
                ip += 4;
            }
            if (offset == 0 || (size_t)(op - dst) < offset ||
                (size_t)(oend - op) < len) return -1;
            const uint8_t* match = op - offset;
            while (len--) *op++ = *match++;
        }
    }
    return (op == oend) ? (int64_t)ulen : -1;
}

static uint8_t* snappy_emit_literal(uint8_t* op, uint8_t* oend,
                                    const uint8_t* p, size_t len) {
    while (len) {
        size_t chunk = len;  // literal tags address up to 2^32
        size_t header = chunk <= 60 ? 1 : (chunk <= 0xff ? 2 : (chunk <= 0xffff ? 3 : (chunk <= 0xffffff ? 4 : 5)));
        if ((size_t)(oend - op) < header + chunk) return nullptr;
        if (chunk <= 60) {
            *op++ = (uint8_t)((chunk - 1) << 2);
        } else {
            uint32_t nb = (uint32_t)header - 1;
            *op++ = (uint8_t)((59 + nb) << 2);
            uint32_t v = (uint32_t)(chunk - 1);
            for (uint32_t i = 0; i < nb; i++) { *op++ = (uint8_t)v; v >>= 8; }
        }
        memcpy(op, p, chunk);
        op += chunk;
        p += chunk;
        len -= chunk;
    }
    return op;
}

int64_t ark_snappy_compress(const uint8_t* src, size_t n,
                            uint8_t* dst, size_t cap) {
    uint8_t* op = dst;
    uint8_t* oend = dst + cap;
    uint64_t v = n;
    do {
        if (op >= oend) return -1;
        uint8_t b = v & 0x7f;
        v >>= 7;
        *op++ = b | (v ? 0x80 : 0);
    } while (v);
    static thread_local int32_t table[1 << 13];
    size_t base = 0;
    while (base < n) {  // snappy matches within 64KB fragments
        size_t frag = n - base < 65536 ? n - base : 65536;
        const uint8_t* fs = src + base;
        const uint8_t* fe = fs + frag;
        for (size_t i = 0; i < (1 << 13); i++) table[i] = -1;
        const uint8_t* ip = fs;
        const uint8_t* anchor = fs;
        if (frag >= 8) {
            const uint8_t* limit = fe - 4;
            while (ip < limit) {
                uint32_t seq;
                memcpy(&seq, ip, 4);
                uint32_t h = lz4_hash(seq);
                int32_t cand = table[h];
                table[h] = (int32_t)(ip - fs);
                uint32_t cseq;
                if (cand < 0) { ip++; continue; }
                memcpy(&cseq, fs + cand, 4);
                if (cseq != seq) { ip++; continue; }
                const uint8_t* match = fs + cand;
                size_t mlen = 4;
                while (ip + mlen < fe && ip[mlen] == match[mlen]) mlen++;
                op = snappy_emit_literal(op, oend, anchor, (size_t)(ip - anchor));
                if (!op) return -1;
                uint32_t offset = (uint32_t)(ip - match);
                size_t rest = mlen;
                while (rest) {  // 2-byte-offset copies, 1..64 each (all legal)
                    size_t c = rest < 64 ? rest : 64;
                    if (oend - op < 3) return -1;
                    *op++ = (uint8_t)(((c - 1) << 2) | 2);
                    *op++ = (uint8_t)offset;
                    *op++ = (uint8_t)(offset >> 8);
                    rest -= c;
                }
                ip += mlen;
                anchor = ip;
            }
        }
        op = snappy_emit_literal(op, oend, anchor, (size_t)(fe - anchor));
        if (!op) return -1;
        base += frag;
    }
    return op - dst;
}

}  // extern "C"
