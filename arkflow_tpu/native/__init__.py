"""Native tier loader: compiles native.cpp once, binds via ctypes.

Every entry point has a pure-Python fallback, so the engine degrades
gracefully on machines without a toolchain (``available()`` reports which
tier is active). The .so is cached next to the source, keyed by a content
hash of native.cpp — never committed to the repo — so what executes is
always compiled from the reviewed source.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
from pathlib import Path
from typing import Optional

import numpy as np

logger = logging.getLogger("arkflow.native")

_HERE = Path(__file__).parent
_SRC = _HERE / "native.cpp"
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_lib() -> Optional[Path]:
    try:
        digest = hashlib.sha256(_SRC.read_bytes()).hexdigest()[:16]
        so_path = _HERE / f"_native-{digest}.so"
        if so_path.exists():
            return so_path
        for stale in _HERE.glob("_native*.so"):
            if stale.name == so_path.name:
                continue  # a concurrent builder may have just installed it
            try:
                stale.unlink()
            except OSError:
                pass
        with tempfile.TemporaryDirectory() as td:
            tmp_so = Path(td) / "_native.so"
            cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", str(_SRC), "-o", str(tmp_so)]
            res = subprocess.run(cmd, capture_output=True, timeout=120)
            if res.returncode != 0:
                logger.warning("native build failed: %s", res.stderr.decode()[:500])
                return None
            os.replace(tmp_so, so_path)
        return so_path
    except (OSError, subprocess.SubprocessError) as e:
        logger.warning("native build unavailable: %s", e)
        return None


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    so = _build_lib()
    if so is None:
        return None
    try:
        lib = ctypes.CDLL(str(so))
        lib.ark_crc32c.restype = ctypes.c_uint32
        lib.ark_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32]
        lib.ark_hash_tokenize.restype = None
        lib.ark_hash_tokenize.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.ark_pad_gather_i32.restype = None
        lib.ark_pad_gather_i32.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32),
        ]
        for nm in ("ark_lz4_decompress_block", "ark_lz4_compress_block",
                   "ark_snappy_decompress", "ark_snappy_compress"):
            fn = getattr(lib, nm)
            fn.restype = ctypes.c_int64
            fn.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                           ctypes.c_char_p, ctypes.c_size_t]
        lib.ark_xxh32.restype = ctypes.c_uint32
        lib.ark_xxh32.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32]
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.ark_pack_ffd.restype = ctypes.c_int
        lib.ark_pack_ffd.argtypes = [i64p, ctypes.c_int, ctypes.c_int, i64p, i64p]
        lib.ark_pack_fill.restype = None
        lib.ark_pack_fill.argtypes = [
            i32p, ctypes.c_int64, i64p, i64p, i64p,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            i32p, i32p, i32p, i32p, i32p,
        ]
        _LIB = lib
    except OSError as e:
        logger.warning("native load failed: %s", e)
        _LIB = None
    return _LIB


def available() -> bool:
    return _load() is not None


# -- crc32c -----------------------------------------------------------------

_CRC32C_TABLE: Optional[list[int]] = None


def _py_crc32c(data: bytes, crc: int = 0) -> int:
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        _CRC32C_TABLE = table
    crc = ~crc & 0xFFFFFFFF
    for b in data:
        crc = _CRC32C_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return ~crc & 0xFFFFFFFF


def crc32c(data: bytes, crc: int = 0) -> int:
    lib = _load()
    if lib is not None:
        return lib.ark_crc32c(data, len(data), crc)
    return _py_crc32c(data, crc)


# -- batch hash tokenizer ---------------------------------------------------

def hash_tokenize_view(values: np.ndarray, offsets: np.ndarray,
                       max_len: int, vocab_size: int):
    """Zero-copy native batch tokenize over an Arrow-style buffer pair.

    ``values`` is the concatenated uint8 payload buffer, ``offsets`` the n+1
    absolute int64 row boundaries inside it — exactly what
    ``MessageBatch.payload_view`` returns, so the kernel reads the Arrow data
    buffer in place (no ``b"".join``, no per-row bytes objects).
    Returns (ids, mask) int32 [n, max_len]; None if no lib.
    """
    lib = _load()
    if lib is None:
        return None
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    values = np.ascontiguousarray(values, dtype=np.uint8)
    n = len(offsets) - 1
    ids = np.zeros((n, max_len), np.int32)
    mask = np.zeros((n, max_len), np.int32)
    lib.ark_hash_tokenize(
        values.ctypes.data_as(ctypes.c_char_p),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, max_len, vocab_size,
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        mask.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return ids, mask


def hash_tokenize_batch(texts: list[bytes], max_len: int, vocab_size: int):
    """Native batch tokenize -> (ids, mask) int32 [n, max_len]; None if no lib."""
    if _load() is None:
        return None
    offsets = np.zeros(len(texts) + 1, np.int64)
    np.cumsum([len(t) for t in texts], out=offsets[1:])
    values = np.frombuffer(b"".join(texts), dtype=np.uint8)
    return hash_tokenize_view(values, offsets, max_len, vocab_size)


# -- block compression codecs (Kafka snappy/lz4; framing lives in
# -- arkflow_tpu/utils/xcodecs.py, which also owns the Python fallbacks) -----

def lz4_decompress_block(src: bytes, max_out: int) -> Optional[bytes]:
    lib = _load()
    if lib is None:
        return None
    dst = ctypes.create_string_buffer(max_out)
    n = lib.ark_lz4_decompress_block(src, len(src), dst, max_out)
    if n < 0:
        raise ValueError("lz4: corrupt block")
    return dst.raw[:n]


def lz4_compress_block(src: bytes) -> Optional[bytes]:
    lib = _load()
    if lib is None:
        return None
    cap = len(src) + len(src) // 255 + 64
    dst = ctypes.create_string_buffer(cap)
    n = lib.ark_lz4_compress_block(src, len(src), dst, cap)
    if n < 0:
        raise ValueError("lz4: compress overflow")
    return dst.raw[:n]


def snappy_decompress(src: bytes, max_out: int) -> Optional[bytes]:
    lib = _load()
    if lib is None:
        return None
    dst = ctypes.create_string_buffer(max(max_out, 1))
    n = lib.ark_snappy_decompress(src, len(src), dst, max_out)
    if n < 0:
        raise ValueError("snappy: corrupt block")
    return dst.raw[:n]


def snappy_compress(src: bytes) -> Optional[bytes]:
    lib = _load()
    if lib is None:
        return None
    cap = 32 + len(src) + len(src) // 6
    dst = ctypes.create_string_buffer(cap)
    n = lib.ark_snappy_compress(src, len(src), dst, cap)
    if n < 0:
        raise ValueError("snappy: compress overflow")
    return dst.raw[:n]


def xxh32(data: bytes, seed: int = 0) -> Optional[int]:
    lib = _load()
    if lib is None:
        return None
    return lib.ark_xxh32(data, len(data), seed)


def pack_tokens_native(ids: np.ndarray, lengths: np.ndarray, seq: int):
    """Native FFD token packer (tpu/packing.py owns the layout contract and
    the reference Python implementation). Returns (out_ids, seg, pos, ex_row,
    ex_pos) or None without the lib. ``lengths`` must be pre-clamped to
    [1, min(seq, ids.shape[1])] (the C++ fill clamps to the row width again
    as a memory-safety backstop, but bin placement uses lengths as given)."""
    lib = _load()
    if lib is None:
        return None
    ids = np.ascontiguousarray(ids, np.int32)
    lengths = np.ascontiguousarray(lengths, np.int64)
    n = int(lengths.shape[0])
    smax = int(ids.shape[1]) if ids.ndim == 2 else 0
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    bin_of = np.empty(n, np.int64)
    start_of = np.empty(n, np.int64)
    n_bins = lib.ark_pack_ffd(
        lengths.ctypes.data_as(i64p), n, seq,
        bin_of.ctypes.data_as(i64p), start_of.ctypes.data_as(i64p))
    out_ids = np.zeros((n_bins, seq), np.int32)
    seg = np.zeros((n_bins, seq), np.int32)
    pos = np.zeros((n_bins, seq), np.int32)
    ex_row = np.empty(n, np.int32)
    ex_pos = np.empty(n, np.int32)
    lib.ark_pack_fill(
        ids.ctypes.data_as(i32p), smax, lengths.ctypes.data_as(i64p),
        bin_of.ctypes.data_as(i64p), start_of.ctypes.data_as(i64p),
        n, seq, n_bins,
        out_ids.ctypes.data_as(i32p), seg.ctypes.data_as(i32p),
        pos.ctypes.data_as(i32p),
        ex_row.ctypes.data_as(i32p), ex_pos.ctypes.data_as(i32p))
    return out_ids, seg, pos, ex_row, ex_pos


def pad_gather_i32(values: np.ndarray, offsets: np.ndarray, seq: int,
                   out_rows: int) -> Optional[np.ndarray]:
    """Native ragged->padded gather; None if no lib."""
    lib = _load()
    if lib is None:
        return None
    n = len(offsets) - 1
    values = np.ascontiguousarray(values, np.int32)
    offsets = np.ascontiguousarray(offsets, np.int64)
    out = np.zeros((out_rows, seq), np.int32)
    lib.ark_pad_gather_i32(
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, seq,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return out
