"""Scalar/aggregate function registry: builtins + user UDFs.

The reference exposes global scalar/aggregate/window UDF registries injected
into every new SessionContext (ref: crates/arkflow-plugin/src/udf/mod.rs:38-43,
scalar_udf.rs:33-63; public API documented in docs/docs/sql/9-udf.md). Here the
same registry feeds both tiers: the native evaluator calls the callable on
Arrow arrays; the sqlite fallback registers it via ``create_function``.

A builtin is a callable ``(args, n) -> pa.Array | scalar`` where ``args`` are
already-evaluated operands (pa.Array of length n, or Python scalar) — most are
thin wrappers over ``pyarrow.compute`` vectorized kernels.
"""

from __future__ import annotations

import hashlib
import json
import math
import time
from typing import Any, Callable, Sequence

import pyarrow as pa
import pyarrow.compute as pc

from arkflow_tpu.errors import UnsupportedSql

ScalarFn = Callable[[Sequence[Any], int], Any]


def as_array(v: Any, n: int) -> pa.Array:
    """Broadcast a Python scalar to an Arrow array of length n."""
    if isinstance(v, (pa.Array, pa.ChunkedArray)):
        return v.combine_chunks() if isinstance(v, pa.ChunkedArray) else v
    if v is None:
        return pa.nulls(n)
    return pa.repeat(pa.scalar(v), n)


def _all_scalar(args: Sequence[Any]) -> bool:
    return not any(isinstance(a, (pa.Array, pa.ChunkedArray)) for a in args)


def _wrap1(kernel):
    def fn(args, n):
        (x,) = args
        if _all_scalar(args):
            return kernel(pa.scalar(x)).as_py() if x is not None else None
        return kernel(as_array(x, n))

    return fn


# -- string helpers --------------------------------------------------------

def _substr(args, n):
    s = as_array(args[0], n)
    start = args[1] if not isinstance(args[1], pa.Array) else None
    if start is None:
        raise UnsupportedSql("substr start must be a literal")
    start = int(start)
    py_start = start - 1 if start > 0 else 0  # SQL is 1-based
    if len(args) >= 3:
        length = int(args[2])
        return pc.utf8_slice_codeunits(s, py_start, py_start + length)
    return pc.utf8_slice_codeunits(s, py_start)


def _concat(args, n):
    arrs = [pc.cast(as_array(a, n), pa.string()) for a in args]
    return pc.binary_join_element_wise(*arrs, "", null_handling="replace", null_replacement="")


def _coalesce(args, n):
    out = as_array(args[0], n)
    for a in args[1:]:
        out = pc.if_else(pc.is_valid(out), out, as_array(a, n))
    return out


def _nullif(args, n):
    a, b = as_array(args[0], n), as_array(args[1], n)
    return pc.if_else(pc.equal(a, b), pa.nulls(n, a.type), a)


def _round(args, n):
    x = as_array(args[0], n)
    digits = int(args[1]) if len(args) > 1 else 0
    return pc.round(x, ndigits=digits)


def _split_part(args, n):
    s, sep, idx = as_array(args[0], n), str(args[1]), int(args[2])
    parts = pc.split_pattern(s, sep)
    return pc.list_element(parts, idx - 1)


def _json_get(args, n, extract=None):
    """Row-wise JSON field extraction from a string/binary column (fallback-speed)."""
    s = as_array(args[0], n)
    key = args[1]
    if isinstance(key, pa.Array):
        raise UnsupportedSql("json key must be a literal")
    out = []
    for v in s:
        pv = v.as_py()
        if pv is None:
            out.append(None)
            continue
        if isinstance(pv, bytes):
            pv = pv.decode("utf-8", "replace")
        try:
            doc = json.loads(pv)
            cur: Any = doc
            for part in str(key).split("."):
                if isinstance(cur, dict):
                    cur = cur.get(part)
                elif isinstance(cur, list) and part.lstrip("-").isdigit():
                    i = int(part)
                    cur = cur[i] if -len(cur) <= i < len(cur) else None
                else:
                    cur = None
            out.append(extract(cur) if extract else cur)
        except (ValueError, TypeError):
            out.append(None)
    if extract is None:
        out = [json.dumps(v) if isinstance(v, (dict, list)) else v for v in out]
        try:
            # homogeneous scalars keep their JSON type (ints stay ints —
            # what VRL's parse_json!(.m).path yields); mixed types fall
            # back to the string form. This is the DYNAMIC variant
            # (json_get_dyn): only VRL lowers to it, where evolving column
            # types are part of the language. The SQL-facing json_get keeps
            # the always-string contract so a streaming query's output
            # schema cannot flip batch-to-batch (advisor r3).
            return pa.array(out)
        except (pa.ArrowInvalid, pa.ArrowTypeError):
            return pa.array([None if v is None else str(v) for v in out],
                            type=pa.string())
    return pa.array(out)


def _json_to_str(v):
    """Stable string form for the SQL-facing json_get: JSON text for
    containers/bools, plain text for scalars, NULL stays NULL."""
    if v is None:
        return None
    if isinstance(v, (dict, list, bool)):
        return json.dumps(v)
    return str(v)


def _split(args, n):
    """split(text, sep) -> list<string> column (VRL's split; Arrow-native)."""
    return pc.split_pattern(as_array(args[0], n), pattern=str(args[1]))


def _join(args, n):
    """join(list, sep) -> string column (VRL's join over split output)."""
    return pc.binary_join(as_array(args[0], n), str(args[1]))


def _list_get(args, n):
    """list_get(list, i) -> element i (0-based; out-of-range/null -> NULL,
    VRL's indexing semantics rather than an error)."""
    arr = as_array(args[0], n)
    idx = args[1]
    if isinstance(idx, pa.Array):
        raise UnsupportedSql("list index must be a literal")
    idx = int(idx)
    lens = pc.list_value_length(arr)
    # guard: pc.list_element errors on out-of-range, VRL yields null — mask
    # short lists to empty via a validity filter built row-wise only when
    # some row is short (common case stays fully vectorized)
    ok = pc.fill_null(pc.greater(lens, idx), False)
    if idx >= 0 and bool(pc.min(ok).as_py() if n else True):
        return pc.list_element(arr, idx)
    out = []
    for v in arr:
        pv = v.as_py()
        out.append(pv[idx] if pv is not None and -len(pv) <= idx < len(pv) else None)
    # pin the element type: an all-out-of-range batch must not flip the
    # column to null-type (schema stability, like _json_get's contract)
    return pa.array(out, type=arr.type.value_type)


def _merge(args, n):
    """merge(a, b) -> shallow-merged JSON object text (b's keys win), the
    columnar form of VRL's object merge (ref vrl.rs runtime): operands are
    JSON text columns (e.g. raw payloads). An invalid/non-object operand is
    treated as the empty object (so the other side passes through); NULL is
    returned only when BOTH operands are invalid/NULL. This is deliberately
    more forgiving than reference VRL, which errors on non-object operands."""
    a, b = as_array(args[0], n), as_array(args[1], n)

    def load(v):
        pv = v.as_py()
        if pv is None:
            return None
        if isinstance(pv, bytes):
            pv = pv.decode("utf-8", "replace")
        try:
            doc = json.loads(pv)
        except (ValueError, TypeError):
            return None
        return doc if isinstance(doc, dict) else None

    out = []
    for va, vb in zip(a, b):
        da, db = load(va), load(vb)
        if da is None and db is None:
            out.append(None)
        else:
            out.append(json.dumps({**(da or {}), **(db or {})}))
    return pa.array(out, type=pa.string())


def encode_json_array(arr: pa.Array) -> pa.Array:
    """Public vectorized entry for ``encode_json`` over one Arrow array:
    JSON text per row, NULL stays NULL. Shared with the codec layer's
    default row-JSON encoding (plugins/codec/helper.py) so both tiers ride
    the same cast-vectorized int/bool fast path."""
    return _encode_json([arr], len(arr))


def _encode_json(args, n):
    """encode_json(x) -> JSON text per row: lists/structs/scalars serialize,
    NULL stays NULL (VRL's encode_json). Integer/boolean columns vectorize
    through ``pc.cast`` (their Arrow string form IS their JSON form); every
    other type takes the row-wise reference pass."""
    arr = as_array(args[0], n)
    if pa.types.is_boolean(arr.type) or pa.types.is_integer(arr.type):
        return pc.cast(arr, pa.string())

    def debytes(pv):
        # bytes can hide anywhere (binary columns split to list<binary>):
        # decode recursively or json.dumps raises and kills the batch
        if isinstance(pv, bytes):
            return pv.decode("utf-8", "replace")
        if isinstance(pv, list):
            return [debytes(x) for x in pv]
        if isinstance(pv, dict):
            return {debytes(k): debytes(v) for k, v in pv.items()}
        return pv

    def enc(pv):
        return None if pv is None else json.dumps(debytes(pv), default=str)

    return pa.array([enc(v.as_py()) for v in arr], type=pa.string())


def _mod(args, n):
    a, b = as_array(args[0], n), as_array(args[1], n)
    return pc.subtract(a, pc.multiply(pc.cast(pc.floor(pc.divide(pc.cast(a, pa.float64()), pc.cast(b, pa.float64()))), b.type), b))


def _fold(kernel, args, n):
    out = as_array(args[0], n)
    for a in args[1:]:
        out = kernel(out, as_array(a, n))
    return out


_BUILTINS: dict[str, ScalarFn] = {
    # math
    "abs": _wrap1(pc.abs),
    "ceil": _wrap1(pc.ceil),
    "ceiling": _wrap1(pc.ceil),
    "floor": _wrap1(pc.floor),
    "sqrt": _wrap1(pc.sqrt),
    "exp": _wrap1(pc.exp),
    "ln": _wrap1(pc.ln),
    "log10": _wrap1(pc.log10),
    "log2": _wrap1(pc.log2),
    "sign": _wrap1(pc.sign),
    "round": _round,
    "power": lambda args, n: pc.power(as_array(args[0], n), as_array(args[1], n)),
    "pow": lambda args, n: pc.power(as_array(args[0], n), as_array(args[1], n)),
    "mod": _mod,
    # string
    "upper": _wrap1(pc.utf8_upper),
    "lower": _wrap1(pc.utf8_lower),
    "length": _wrap1(pc.utf8_length),
    "char_length": _wrap1(pc.utf8_length),
    "character_length": _wrap1(pc.utf8_length),
    "octet_length": _wrap1(pc.binary_length),
    "trim": _wrap1(pc.utf8_trim_whitespace),
    "ltrim": _wrap1(pc.utf8_ltrim_whitespace),
    "rtrim": _wrap1(pc.utf8_rtrim_whitespace),
    "reverse": _wrap1(pc.utf8_reverse),
    "substr": _substr,
    "substring": _substr,
    "concat": _concat,
    "replace": lambda args, n: pc.replace_substring(as_array(args[0], n), pattern=str(args[1]), replacement=str(args[2])),
    "starts_with": lambda args, n: pc.starts_with(as_array(args[0], n), pattern=str(args[1])),
    "ends_with": lambda args, n: pc.ends_with(as_array(args[0], n), pattern=str(args[1])),
    "strpos": lambda args, n: pc.add(pc.find_substring(as_array(args[0], n), pattern=str(args[1])), 1),
    "lpad": lambda args, n: pc.utf8_lpad(as_array(args[0], n), width=int(args[1]), padding=str(args[2]) if len(args) > 2 else " "),
    "rpad": lambda args, n: pc.utf8_rpad(as_array(args[0], n), width=int(args[1]), padding=str(args[2]) if len(args) > 2 else " "),
    "split_part": _split_part,
    # list / object tier (VRL split/join/merge/encode_json on Arrow columns)
    "split": _split,
    "join": _join,
    "array_join": _join,
    "list_get": _list_get,
    "merge": _merge,
    "encode_json": _encode_json,
    # null handling / misc
    "coalesce": _coalesce,
    "ifnull": _coalesce,
    "nvl": _coalesce,
    "nullif": _nullif,
    "greatest": lambda args, n: _fold(pc.max_element_wise, args, n),
    "least": lambda args, n: _fold(pc.min_element_wise, args, n),
    # time
    "now": lambda args, n: time.time(),
    "unix_millis": lambda args, n: int(time.time() * 1000),
    "current_timestamp": lambda args, n: time.time(),
    # json (for the __value__ payload column)
    "json_get": lambda args, n: _json_get(args, n, extract=_json_to_str),
    "json_get_dyn": lambda args, n: _json_get(args, n),
    "json_get_str": lambda args, n: _json_get(args, n, extract=lambda v: None if v is None else str(v)),
    "json_get_int": lambda args, n: _json_get(args, n, extract=lambda v: int(v) if isinstance(v, (int, float)) and not isinstance(v, bool) else None),
    "json_get_float": lambda args, n: _json_get(args, n, extract=lambda v: float(v) if isinstance(v, (int, float)) and not isinstance(v, bool) else None),
    "json_get_bool": lambda args, n: _json_get(args, n, extract=lambda v: v if isinstance(v, bool) else None),
    # VRL-style fallible parsers: failures become NULL, so the VRL idiom
    # `to_int(.x) ?? 0` maps to `coalesce(parse_int(x), 0)` (see PARITY.md)
    "parse_int": lambda args, n: _parse_int(args, n),
    "parse_float": lambda args, n: _rowwise1(args, n, _to_float),
    "parse_timestamp": lambda args, n: _parse_timestamp(args, n),
    "format_timestamp": lambda args, n: _format_timestamp(args, n),
    "regex_match": lambda args, n: _regex_match(args, n),
    "regex_extract": lambda args, n: _regex_extract(args, n),
    "parse_key_value": lambda args, n: _parse_key_value(args, n),
    "parse_url": lambda args, n: _parse_url(args, n),
    "parse_syslog": lambda args, n: _parse_syslog(args, n),
    "md5": lambda args, n: _rowwise1(args, n, lambda v: hashlib.md5(_as_bytes(v)).hexdigest(), raw=True),
    "sha256": lambda args, n: _rowwise1(args, n, lambda v: hashlib.sha256(_as_bytes(v)).hexdigest(), raw=True),
    "to_string": lambda args, n: _rowwise1(args, n, str),
}


# -- VRL-style fallible parser implementations ------------------------------

def _pylist(v, n):
    arr = as_array(v, n)
    return arr.to_pylist()


def _as_bytes(v):
    """Hash inputs keep their raw bytes (a lossy decode would change the
    digest); strings hash their utf-8 encoding, matching VRL/`md5sum`."""
    return bytes(v) if isinstance(v, (bytes, bytearray)) else str(v).encode()


def _rowwise1(args, n, fn, raw=False):
    out = []
    for v in _pylist(args[0], n):
        if v is None:
            out.append(None)
            continue
        if isinstance(v, bytes) and not raw:
            v = v.decode(errors="replace")
        try:
            out.append(fn(v))
        except Exception:
            # the fallible-parser contract (PARITY.md): a bad row yields
            # NULL, never aborts the batch (OverflowError from int(inf),
            # OSError from out-of-range gmtime, IndexError from a missing
            # regex group, ...)
            out.append(None)
    return pa.array(out)


def _to_float(v):
    return float(v)


def _parse_int(args, n):
    base = int(args[1]) if len(args) > 1 else 10

    def conv(v):
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return int(v)
        return int(str(v).strip(), base)

    return _rowwise1(args, n, conv)


def _parse_timestamp(args, n):
    """parse_timestamp(x, fmt) -> epoch seconds (UTC) or NULL."""
    import calendar
    import time as _t

    fmt = str(args[1]) if len(args) > 1 else "%Y-%m-%dT%H:%M:%S"

    def conv(v):
        return float(calendar.timegm(_t.strptime(str(v).strip(), fmt)))

    return _rowwise1(args, n, conv)


def _format_timestamp(args, n):
    import time as _t

    fmt = str(args[1]) if len(args) > 1 else "%Y-%m-%dT%H:%M:%S"
    return _rowwise1(args, n, lambda v: _t.strftime(fmt, _t.gmtime(float(v))))


_REGEX_CACHE: dict[str, Any] = {}


def _compiled(pattern: str):
    import re

    rx = _REGEX_CACHE.get(pattern)
    if rx is None:
        rx = _REGEX_CACHE[pattern] = re.compile(pattern)
    return rx


def _regex_match(args, n):
    rx = _compiled(str(args[1]))
    return _rowwise1(args, n, lambda v: rx.search(str(v)) is not None)


def _regex_extract(args, n):
    """regex_extract(x, pattern [, group]) — group index or name; default 1
    when the pattern has groups, else the whole match."""
    rx = _compiled(str(args[1]))
    group: Any = args[2] if len(args) > 2 else (1 if rx.groups else 0)
    if isinstance(group, float):
        group = int(group)

    def conv(v):
        m = rx.search(str(v))
        return None if m is None else m.group(group)

    return _rowwise1(args, n, conv)


def _split_pairs(text: str, pair_sep: str):
    """Split on pair_sep outside double quotes (logfmt quoting)."""
    out, cur, quoted = [], [], False
    i, sep_len = 0, len(pair_sep)
    while i < len(text):
        ch = text[i]
        if quoted and ch == "\\" and i + 1 < len(text):
            cur.append(ch)
            cur.append(text[i + 1])  # escaped char (incl. \") stays in-value
            i += 2
        elif ch == '"':
            quoted = not quoted
            cur.append(ch)
            i += 1
        elif not quoted and text.startswith(pair_sep, i):
            out.append("".join(cur))
            cur = []
            i += sep_len
        else:
            cur.append(ch)
            i += 1
    out.append("".join(cur))
    return out


def _parse_key_value(args, n):
    """parse_key_value(x, key [, pair_sep, kv_sep]) — logfmt-style lookup;
    double-quoted values may contain the pair separator."""
    key = str(args[1])
    pair_sep = str(args[2]) if len(args) > 2 else " "
    kv_sep = str(args[3]) if len(args) > 3 else "="

    import re as _re

    def conv(v):
        for pair in _split_pairs(str(v), pair_sep):
            k, sep, val = pair.partition(kv_sep)
            if sep and k.strip() == key:
                val = val.strip()
                if len(val) >= 2 and val[0] == '"' and val[-1] == '"':
                    val = val[1:-1]  # the delimiting quotes only
                return _re.sub(r"\\(.)", r"\1", val)  # \" -> ", \\ -> \
        return None

    return _rowwise1(args, n, conv)


_SYSLOG_3164 = None
_SYSLOG_5424 = None


def _parse_syslog(args, n):
    """parse_syslog(line, part): RFC 5424 and legacy RFC 3164 lines.
    Parts: severity, facility, timestamp, hostname, appname, procid, msgid,
    message, version. Unparseable rows -> NULL (fallible, like the VRL fn)."""
    global _SYSLOG_3164, _SYSLOG_5424
    import re as _re

    if _SYSLOG_5424 is None:
        _SYSLOG_5424 = _re.compile(
            r"^<(?P<pri>\d{1,3})>(?P<version>\d)\s+"
            r"(?P<timestamp>\S+)\s+(?P<hostname>\S+)\s+(?P<appname>\S+)\s+"
            r"(?P<procid>\S+)\s+(?P<msgid>\S+)\s+"
            r"(?P<sd>-|(?:\[.*?\])+)\s*(?P<message>.*)$", _re.DOTALL)
        _SYSLOG_3164 = _re.compile(
            r"^<(?P<pri>\d{1,3})>"
            r"(?P<timestamp>[A-Z][a-z]{2}\s+\d{1,2}\s\d{2}:\d{2}:\d{2})\s+"
            r"(?P<hostname>\S+)\s+"
            r"(?P<appname>[^\s:\[]+)(?:\[(?P<procid>\d+)\])?:?\s*"
            r"(?P<message>.*)$", _re.DOTALL)
    s = as_array(args[0], n)
    key = args[1]
    if isinstance(key, pa.Array):
        raise UnsupportedSql("parse_syslog part must be a literal")
    key = str(key)

    fast = _parse_syslog_vector(s, key)
    if fast is not None:
        return fast

    def one(v):
        # fallible-parser contract: a bad row (wrong type, no match) yields
        # NULL, never aborts the batch
        if v is None:
            return None
        if isinstance(v, bytes):
            v = v.decode("utf-8", "replace")
        try:
            m = _SYSLOG_5424.match(v) or _SYSLOG_3164.match(v)
        except TypeError:
            return None
        if m is None:
            return None
        d = m.groupdict()
        pri = int(d["pri"])
        if key == "severity":
            return pri & 7
        if key == "facility":
            return pri >> 3
        if key == "version":
            return int(d["version"]) if d.get("version") else None
        val = d.get(key)
        return None if val in (None, "-") else val

    return pa.array([one(v.as_py()) for v in s])


def _parse_syslog_vector(s: pa.Array, key: str):
    """Vectorized parse_syslog: one ``pc.extract_regex`` (RE2) pass per
    pattern over the whole column instead of a Python match per row. Returns
    None when the kernels can't serve the input (non-UTF-8 binary, exotic
    type, old pyarrow) — the caller falls back to the row-wise reference."""
    try:
        if pa.types.is_binary(s.type) or pa.types.is_large_binary(s.type):
            s = pc.cast(s, pa.string())  # strict: invalid UTF-8 -> fallback
        elif not (pa.types.is_string(s.type) or pa.types.is_large_string(s.type)):
            return None
        # (?s) = DOTALL, matching the compiled Python patterns' flag
        m5424 = pc.extract_regex(s, pattern="(?s)" + _SYSLOG_5424.pattern)
        m3164 = pc.extract_regex(s, pattern="(?s)" + _SYSLOG_3164.pattern)
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError, AttributeError):
        return None
    is5424 = pc.is_valid(m5424)
    if key in ("severity", "facility"):
        pri = pc.if_else(is5424, pc.struct_field(m5424, "pri"),
                         pc.struct_field(m3164, "pri"))
        pri = pc.cast(pri, pa.int64())
        return pc.bit_wise_and(pri, 7) if key == "severity" else pc.shift_right(pri, 3)
    if key == "version":  # RFC 3164 lines carry no version
        return pc.cast(pc.struct_field(m5424, "version"), pa.int64())
    in5424 = key in _SYSLOG_5424.groupindex
    in3164 = key in _SYSLOG_3164.groupindex
    if not (in5424 or in3164):
        return pa.nulls(len(s), pa.string())
    nulls = pa.nulls(len(s), pa.string())
    val = pc.if_else(
        is5424,
        pc.struct_field(m5424, key) if in5424 else nulls,
        pc.struct_field(m3164, key) if in3164 else nulls,
    )
    if key == "procid":
        # RE2 reports the unmatched optional 3164 group as "", Python as None
        val = pc.if_else(pc.equal(val, ""), pa.scalar(None, pa.string()), val)
    # the RFC 5424 nil value "-" reads as NULL, like the row-wise path
    return pc.if_else(pc.equal(val, "-"), pa.scalar(None, pa.string()), val)


def _parse_url(args, n):
    from urllib.parse import urlparse

    part = str(args[1]) if len(args) > 1 else "host"

    def conv(v):
        u = urlparse(str(v))
        val = {"scheme": u.scheme, "host": u.hostname, "port": u.port,
               "path": u.path, "query": u.query, "fragment": u.fragment,
               "username": u.username}.get(part)
        return None if val in (None, "") else val

    return _rowwise1(args, n, conv)


#: Aggregates the native GROUP BY planner maps onto pyarrow hash kernels.
NATIVE_AGGREGATES = {
    "count": "count",
    "sum": "sum",
    "min": "min",
    "max": "max",
    "avg": "mean",
    "mean": "mean",
    "stddev": "stddev",
    "variance": "variance",
    "var": "variance",
    "first_value": "first",
    "last_value": "last",
    "approx_distinct": "count_distinct",
}

# -- user UDFs -------------------------------------------------------------

_SCALAR_UDFS: dict[str, tuple[Callable, bool]] = {}
_AGGREGATE_UDFS: dict[str, Callable] = {}


def register_scalar_udf(name: str, fn: Callable, vectorized: bool = False) -> None:
    """Register a scalar UDF usable from any SQL processor.

    ``vectorized=True``: ``fn(*arrow_arrays) -> arrow array``.
    ``vectorized=False``: ``fn(*python_scalars) -> python scalar`` applied row-wise.
    (Public extension API — ref docs/docs/sql/9-udf.md.)
    """
    _SCALAR_UDFS[name.lower()] = (fn, vectorized)


def register_aggregate_udf(name: str, fn: Callable) -> None:
    """Register an aggregate UDF: ``fn(list_of_python_values) -> scalar``."""
    _AGGREGATE_UDFS[name.lower()] = fn


def get_aggregate_udf(name: str):
    return _AGGREGATE_UDFS.get(name.lower())


def scalar_udfs() -> dict[str, tuple[Callable, bool]]:
    return dict(_SCALAR_UDFS)


def call_scalar(name: str, args: Sequence[Any], n: int) -> Any:
    """Dispatch a scalar function call: builtins first, then UDFs."""
    fn = _BUILTINS.get(name)
    if fn is not None:
        return fn(args, n)
    udf = _SCALAR_UDFS.get(name)
    if udf is not None:
        f, vectorized = udf
        if vectorized:
            return f(*[as_array(a, n) for a in args])
        cols = [as_array(a, n).to_pylist() for a in args]
        return pa.array([f(*row) for row in zip(*cols)] if cols else [f() for _ in range(n)])
    raise UnsupportedSql(f"unknown function {name!r}")


def has_function(name: str) -> bool:
    return name in _BUILTINS or name in _SCALAR_UDFS
