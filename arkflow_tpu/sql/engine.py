"""SessionContext + ContextPool: the user-facing SQL entry points.

``SessionContext.sql(query)`` mirrors DataFusion's batch-table contract:
register Arrow batches under table names, run a query, get a batch back
(ref: crates/arkflow-plugin/src/processor/sql.rs:112-129). Execution tries the
native Arrow planner first and silently reroutes to the sqlite fallback on
``UnsupportedSql``.

``ContextPool`` reproduces the reference's fixed pool of contexts
(ref context_pool.rs:30-131) as an async context manager over a semaphore.
"""

from __future__ import annotations

import asyncio
import contextlib
from typing import Optional

from arkflow_tpu.batch import MessageBatch
from arkflow_tpu.errors import UnsupportedSql
from arkflow_tpu.sql.fallback import execute_fallback
from arkflow_tpu.sql.parser import assert_query_only, parse_select
from arkflow_tpu.sql.planner import execute_select


class SessionContext:
    def __init__(self) -> None:
        self._tables: dict[str, MessageBatch] = {}

    def register_batch(self, name: str, batch: MessageBatch) -> None:
        self._tables[name] = batch

    def deregister(self, name: str) -> None:
        self._tables.pop(name, None)

    def deregister_all(self) -> None:
        self._tables.clear()

    def table_names(self) -> list[str]:
        return sorted(self._tables)

    def sql(self, query: str) -> MessageBatch:
        """Execute a read-only query over the registered tables."""
        assert_query_only(query)
        try:
            sel = parse_select(query)
            return execute_select(sel, self._tables)
        except UnsupportedSql:
            return execute_fallback(query, self._tables)


class ContextPool:
    """Fixed pool of SessionContexts (ref context_pool.rs: 4 contexts, spin-wait).

    The asyncio equivalent uses a semaphore instead of a spin-wait; contexts
    are handed out round-robin and wiped (tables deregistered) on release.
    """

    def __init__(self, size: int = 4):
        if size <= 0:
            raise ValueError("pool size must be positive")
        self._contexts: list[SessionContext] = [SessionContext() for _ in range(size)]
        self._free: asyncio.Queue[SessionContext] = asyncio.Queue()
        for c in self._contexts:
            self._free.put_nowait(c)

    @contextlib.asynccontextmanager
    async def acquire(self):
        ctx = await self._free.get()
        try:
            yield ctx
        finally:
            ctx.deregister_all()
            self._free.put_nowait(ctx)
