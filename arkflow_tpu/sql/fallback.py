"""sqlite3 fallback tier: full-dialect SQL over bridged Arrow batches.

Covers what the native Arrow planner declines — subqueries, CTEs, UNION,
explicit window frames, running MIN/MAX — by materialising registered batches into an
in-memory sqlite database, executing there, and lifting the result back to
Arrow. Row-materialising and therefore slow; the native tier owns the hot
path. User UDFs (``arkflow_tpu.sql.functions``) are bridged via
``create_function`` so both tiers see the same function surface.
"""

from __future__ import annotations

import sqlite3
from typing import Any, Mapping

import pyarrow as pa

from arkflow_tpu.batch import MessageBatch
from arkflow_tpu.errors import ArkError
from arkflow_tpu.sql.functions import as_array, get_aggregate_udf, scalar_udfs
from arkflow_tpu.sql.parser import assert_query_only


def _sqlite_type(t: pa.DataType) -> str:
    if pa.types.is_integer(t) or pa.types.is_boolean(t):
        return "INTEGER"
    if pa.types.is_floating(t) or pa.types.is_decimal(t):
        return "REAL"
    if pa.types.is_binary(t) or pa.types.is_large_binary(t):
        return "BLOB"
    return "TEXT"


def _to_cell(v: Any) -> Any:
    if v is None or isinstance(v, (int, float, str, bytes)):
        return v
    if isinstance(v, bool):
        return int(v)
    return str(v)


_READONLY_OPS = {
    sqlite3.SQLITE_SELECT,
    sqlite3.SQLITE_READ,
    sqlite3.SQLITE_FUNCTION,
    sqlite3.SQLITE_RECURSIVE,
}


def _readonly_authorizer(action, *args):
    return sqlite3.SQLITE_OK if action in _READONLY_OPS else sqlite3.SQLITE_DENY


class _AggAdapter:
    """Bridges ``fn(list_of_values) -> scalar`` UDFs onto sqlite's step/finalize."""

    def __init__(self, fn):
        self.fn = fn
        self.values: list[Any] = []

    def step(self, *args):
        self.values.append(args[0] if len(args) == 1 else args)

    def finalize(self):
        return _to_cell(self.fn(self.values))


def execute_fallback(sql: str, tables: Mapping[str, MessageBatch]) -> MessageBatch:
    assert_query_only(sql)
    conn = sqlite3.connect(":memory:")
    try:
        conn.execute("PRAGMA temp_store=MEMORY")
        for name, batch in tables.items():
            _load_table(conn, name, batch)
        for name, (fn, vectorized) in scalar_udfs().items():
            conn.create_function(name, -1, _wrap_udf(fn, vectorized))
        for name in _aggregate_udf_names():
            fn = get_aggregate_udf(name)
            conn.create_aggregate(name, -1, _make_agg_class(fn))
        # defence in depth: after our own table loads, lock the connection to
        # read-only operations (blocks ATTACH/DDL/DML even if a statement
        # slips past assert_query_only)
        conn.set_authorizer(_readonly_authorizer)
        try:
            cur = conn.execute(sql)
        except sqlite3.Error as e:
            raise ArkError(f"SQL error (fallback engine): {e}") from e
        names = [d[0] for d in cur.description] if cur.description else []
        rows = cur.fetchall()
        cols = list(zip(*rows)) if rows else [[] for _ in names]
        arrays = []
        for i, _ in enumerate(names):
            vals = list(cols[i]) if rows else []
            arrays.append(pa.array(vals))
        # de-duplicate output names the way DataFusion would (a, a -> a, a:1)
        seen: dict[str, int] = {}
        uniq = []
        for nm in names:
            if nm in seen:
                seen[nm] += 1
                uniq.append(f"{nm}:{seen[nm]}")
            else:
                seen[nm] = 0
                uniq.append(nm)
        return MessageBatch(pa.RecordBatch.from_arrays(arrays, names=uniq))
    finally:
        conn.close()


def _aggregate_udf_names() -> list[str]:
    from arkflow_tpu.sql import functions

    return list(functions._AGGREGATE_UDFS)


def _make_agg_class(fn):
    class Agg(_AggAdapter):
        def __init__(self):
            super().__init__(fn)

    return Agg


def _wrap_udf(fn, vectorized: bool):
    if not vectorized:
        return lambda *args: _to_cell(fn(*args))

    def call(*args):
        arrs = [pa.array([a]) for a in args]
        out = as_array(fn(*arrs), 1)
        return _to_cell(out[0].as_py())

    return call


def _load_table(conn: sqlite3.Connection, name: str, batch: MessageBatch) -> None:
    rb = batch.record_batch
    qname = '"' + name.replace('"', '""') + '"'
    col_defs = ", ".join(
        f'"{f.name}" {_sqlite_type(f.type)}' for f in rb.schema
    )
    if not col_defs:
        col_defs = '"__empty__" INTEGER'
    conn.execute(f"CREATE TABLE {qname} ({col_defs})")
    if rb.num_rows == 0 or rb.num_columns == 0:
        return
    placeholders = ", ".join("?" for _ in rb.schema)
    cols = [c.to_pylist() for c in rb.columns]
    rows = [tuple(_to_cell(v) for v in row) for row in zip(*cols)]
    conn.executemany(f"INSERT INTO {qname} VALUES ({placeholders})", rows)
