"""Native SELECT execution on pyarrow kernels.

Single-table SELECT / WHERE / GROUP BY / HAVING / ORDER BY / LIMIT / DISTINCT
compiled onto vectorized Arrow compute. Aggregations run on Arrow's hash
kernels via ``Table.group_by``. Scalar-over-aggregate expressions
(``sum(x)/count(*)``) are handled by substituting computed aggregate columns
into the expression tree and re-evaluating on the aggregated table.

Queries outside this shape raise ``UnsupportedSql`` and the engine reroutes
them to the sqlite fallback.
"""

from __future__ import annotations

from typing import Any, Optional

import pyarrow as pa
import pyarrow.compute as pc

from arkflow_tpu.batch import MessageBatch
from arkflow_tpu.errors import UnsupportedSql
from arkflow_tpu.sql import ast
from arkflow_tpu.sql.eval import Evaluator
from arkflow_tpu.sql.functions import NATIVE_AGGREGATES, as_array, has_function


def render(e: ast.Expr) -> str:
    """Stable display name for an unaliased expression column."""
    if isinstance(e, ast.Column):
        return e.name
    if isinstance(e, ast.Literal):
        return repr(e.value)
    if isinstance(e, ast.Func):
        inner = "*" if e.is_star else ", ".join(render(a) for a in e.args)
        d = "DISTINCT " if e.distinct else ""
        return f"{e.name}({d}{inner})"
    if isinstance(e, ast.Binary):
        return f"{render(e.left)} {e.op} {render(e.right)}"
    if isinstance(e, ast.Unary):
        return f"{e.op} {render(e.operand)}"
    if isinstance(e, ast.Cast):
        return f"cast({render(e.operand)} as {e.type_name})"
    return type(e).__name__.lower()


def _find_aggregates(e: ast.Expr, out: list[ast.Func]) -> None:
    if isinstance(e, ast.Func) and (e.name in NATIVE_AGGREGATES or e.is_star and e.name == "count"):
        if e.name in NATIVE_AGGREGATES or e.is_star:
            out.append(e)
            return  # don't descend into aggregate args
    if isinstance(e, ast.Func) and not has_function(e.name) and not e.is_star:
        # unknown function: could be an aggregate UDF -> not natively plannable
        raise UnsupportedSql(f"unknown function {e.name!r} in native planner")
    for child in _children(e):
        _find_aggregates(child, out)


def _children(e: ast.Expr) -> list[ast.Expr]:
    if isinstance(e, ast.Unary):
        return [e.operand]
    if isinstance(e, ast.Binary):
        return [e.left, e.right]
    if isinstance(e, ast.IsNull):
        return [e.operand]
    if isinstance(e, ast.InList):
        return [e.operand, *e.items]
    if isinstance(e, ast.Between):
        return [e.operand, e.low, e.high]
    if isinstance(e, ast.Func):
        return list(e.args)
    if isinstance(e, ast.Cast):
        return [e.operand]
    if isinstance(e, ast.Case):
        out = list(e.whens and [x for w in e.whens for x in w] or [])
        if e.operand is not None:
            out.append(e.operand)
        if e.otherwise is not None:
            out.append(e.otherwise)
        return out
    return []


def _substitute(e: ast.Expr, mapping: dict[ast.Expr, ast.Column]) -> ast.Expr:
    """Replace mapped subtrees (group keys / aggregates) with column refs."""
    if e in mapping:
        return mapping[e]
    if isinstance(e, ast.Unary):
        return ast.Unary(e.op, _substitute(e.operand, mapping))
    if isinstance(e, ast.Binary):
        return ast.Binary(e.op, _substitute(e.left, mapping), _substitute(e.right, mapping))
    if isinstance(e, ast.IsNull):
        return ast.IsNull(_substitute(e.operand, mapping), e.negated)
    if isinstance(e, ast.InList):
        return ast.InList(_substitute(e.operand, mapping), tuple(_substitute(i, mapping) for i in e.items), e.negated)
    if isinstance(e, ast.Between):
        return ast.Between(_substitute(e.operand, mapping), _substitute(e.low, mapping), _substitute(e.high, mapping), e.negated)
    if isinstance(e, ast.Func):
        return ast.Func(e.name, tuple(_substitute(a, mapping) for a in e.args), e.distinct, e.is_star)
    if isinstance(e, ast.Cast):
        return ast.Cast(_substitute(e.operand, mapping), e.type_name)
    if isinstance(e, ast.Case):
        return ast.Case(
            _substitute(e.operand, mapping) if e.operand is not None else None,
            tuple((_substitute(c, mapping), _substitute(v, mapping)) for c, v in e.whens),
            _substitute(e.otherwise, mapping) if e.otherwise is not None else None,
        )
    return e


def execute_select(sel: ast.Select, tables: dict[str, MessageBatch]) -> MessageBatch:
    """Run a parsed single-table SELECT natively; raise UnsupportedSql otherwise."""
    if sel.joins:
        raise UnsupportedSql("joins run on the fallback engine")
    if sel.table is None:
        # SELECT <exprs> without FROM: single-row evaluation
        batch = MessageBatch.from_pydict({})
        ev = Evaluator({}, 1)
        arrays, names = [], []
        for i, item in enumerate(sel.items):
            if isinstance(item.expr, ast.Star):
                raise UnsupportedSql("* without FROM")
            v = ev.eval(item.expr)
            arrays.append(as_array(v, 1))
            names.append(item.alias or render(item.expr))
        return MessageBatch(pa.RecordBatch.from_arrays(arrays, names=names))

    tname = sel.table.name
    batch = tables.get(tname)
    if batch is None:
        raise UnsupportedSql(f"unknown table {tname!r} (registered: {sorted(tables)})")
    alias = sel.table.alias or tname
    rb = batch.record_batch

    # WHERE
    if sel.where is not None:
        ev = Evaluator.for_batch(rb, table=alias)
        mask = ev.eval(sel.where)
        mask = as_array(mask, rb.num_rows)
        if not pa.types.is_boolean(mask.type):
            mask = pc.cast(mask, pa.bool_())
        rb = rb.filter(mask)

    # aggregate?
    aggs: list[ast.Func] = []
    for item in sel.items:
        if not isinstance(item.expr, ast.Star):
            _find_aggregates(item.expr, aggs)
    if sel.having is not None:
        _find_aggregates(sel.having, aggs)
    if sel.group_by or aggs:
        out = _execute_aggregate(sel, rb, alias, aggs)
    else:
        out = _execute_projection(sel, rb, alias)

    # DISTINCT
    if sel.distinct:
        t = pa.Table.from_batches([out])
        t = t.group_by(t.schema.names).aggregate([])
        out = MessageBatch.from_table(t).record_batch

    # ORDER BY
    if sel.order_by:
        out = _order(out, sel, alias, rb)

    # LIMIT/OFFSET
    if sel.offset is not None:
        out = out.slice(sel.offset)
    if sel.limit is not None:
        out = out.slice(0, sel.limit)
    return MessageBatch(out)


def _execute_projection(sel: ast.Select, rb: pa.RecordBatch, alias: str) -> pa.RecordBatch:
    ev = Evaluator.for_batch(rb, table=alias)
    arrays: list[pa.Array] = []
    names: list[str] = []
    for item in sel.items:
        if isinstance(item.expr, ast.Star):
            for i, f in enumerate(rb.schema):
                arrays.append(rb.column(i))
                names.append(f.name)
            continue
        v = ev.eval(item.expr)
        arrays.append(as_array(v, rb.num_rows))
        names.append(item.alias or render(item.expr))
    return pa.RecordBatch.from_arrays(arrays, names=names)


_DISTINCT_AGGS = {"count": "count_distinct"}


def _execute_aggregate(sel: ast.Select, rb: pa.RecordBatch, alias: str, aggs: list[ast.Func]) -> pa.RecordBatch:
    ev = Evaluator.for_batch(rb, table=alias)
    n = rb.num_rows

    # Deduplicate aggregates structurally.
    uniq: list[ast.Func] = []
    for a in aggs:
        if a not in uniq:
            uniq.append(a)

    # Build the pre-aggregation table: key columns + aggregate input columns.
    key_names, key_arrays = [], []
    mapping: dict[ast.Expr, ast.Column] = {}
    for i, g in enumerate(sel.group_by):
        kn = f"__key_{i}"
        key_names.append(kn)
        key_arrays.append(as_array(ev.eval(g), n))
        mapping[g] = ast.Column(kn)

    agg_specs = []  # (input_col_name_or_[], kernel, output_name)
    in_names, in_arrays = [], []
    for i, a in enumerate(uniq):
        out_name = f"__agg_{i}"
        if a.is_star:  # count(*)
            agg_specs.append(([], "count_all", out_name))
        else:
            if len(a.args) != 1:
                raise UnsupportedSql(f"aggregate {a.name} takes exactly one argument natively")
            kernel = NATIVE_AGGREGATES[a.name]
            if a.distinct:
                kernel = _DISTINCT_AGGS.get(a.name)
                if kernel is None:
                    raise UnsupportedSql(f"DISTINCT {a.name} not supported natively")
            col = f"__in_{i}"
            in_names.append(col)
            in_arrays.append(as_array(ev.eval(a.args[0]), n))
            agg_specs.append((col, kernel, out_name))
        mapping[a] = ast.Column(f"__agg_{i}")

    pre = pa.table(dict(zip(key_names + in_names, key_arrays + in_arrays))) if (key_names or in_names) else pa.table({"__dummy__": pa.nulls(n)})

    grouped = pre.group_by(key_names, use_threads=False).aggregate(
        [(c, k) for c, k, _ in agg_specs]
    )
    # pyarrow names results "<col>_<kernel>"; rename to our __agg_i slots.
    rename: dict[str, str] = {}
    for c, k, out_name in agg_specs:
        produced = f"{c}_{k}" if c != [] else k  # ([], "count_all") -> "count_all"
        rename[produced] = out_name
    grouped = grouped.rename_columns([rename.get(nm, nm) for nm in grouped.schema.names])
    agg_rb = MessageBatch.from_table(grouped).record_batch

    # HAVING on the aggregated table.
    if sel.having is not None:
        hev = Evaluator.for_batch(agg_rb)
        mask = as_array(hev.eval(_substitute(sel.having, mapping)), agg_rb.num_rows)
        if not pa.types.is_boolean(mask.type):
            mask = pc.cast(mask, pa.bool_())
        agg_rb = agg_rb.filter(mask)

    # Final projection over key/agg columns.
    fev = Evaluator.for_batch(agg_rb)
    arrays, names = [], []
    for item in sel.items:
        if isinstance(item.expr, ast.Star):
            raise UnsupportedSql("* not valid in aggregate query")
        sub = _substitute(item.expr, mapping)
        _assert_resolved(sub, set(agg_rb.schema.names))
        arrays.append(as_array(fev.eval(sub), agg_rb.num_rows))
        names.append(item.alias or render(item.expr))
    return pa.RecordBatch.from_arrays(arrays, names=names)


def _assert_resolved(e: ast.Expr, available: set[str]) -> None:
    """Every column in a post-aggregation expression must be a key or agg slot."""
    if isinstance(e, ast.Column) and e.name not in available:
        raise UnsupportedSql(
            f"column {e.name!r} must appear in GROUP BY or inside an aggregate"
        )
    for c in _children(e):
        _assert_resolved(c, available)


def _order(out: pa.RecordBatch, sel: ast.Select, alias: str, pre_rb: pa.RecordBatch) -> pa.RecordBatch:
    sort_cols: list[tuple[str, str]] = []
    extra: dict[str, pa.Array] = {}
    tmp = out
    for i, oi in enumerate(sel.order_by):
        direction = "ascending" if oi.asc else "descending"
        e = oi.expr
        if isinstance(e, ast.Literal) and isinstance(e.value, int):
            idx = e.value - 1
            if not (0 <= idx < out.num_columns):
                raise UnsupportedSql(f"ORDER BY position {e.value} out of range")
            sort_cols.append((out.schema.names[idx], direction))
            continue
        if isinstance(e, ast.Column) and e.name in out.schema.names:
            sort_cols.append((e.name, direction))
            continue
        # expression over output (aliases) or, failing that, the source rows
        try:
            v = as_array(Evaluator.for_batch(out).eval(e), out.num_rows)
        except UnsupportedSql:
            if pre_rb.num_rows != out.num_rows:
                raise UnsupportedSql("ORDER BY expression not resolvable against output")
            v = as_array(Evaluator.for_batch(pre_rb, table=alias).eval(e), out.num_rows)
        name = f"__sort_{i}"
        extra[name] = v
        sort_cols.append((name, direction))
    colmap: dict[str, pa.Array] = {}
    for nm, arr in zip(out.schema.names, out.columns):
        colmap.setdefault(nm, arr)
    colmap.update(extra)
    key_t = pa.table({c: colmap[c] for c, _ in sort_cols})
    indices = pc.sort_indices(key_t, sort_keys=sort_cols)
    return out.take(indices)
