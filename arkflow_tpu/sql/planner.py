"""Native SELECT execution on pyarrow kernels.

SELECT / WHERE / JOIN / GROUP BY / HAVING / window functions / ORDER BY /
LIMIT / DISTINCT compiled onto vectorized Arrow compute. Aggregations run on
Arrow's hash kernels via ``Table.group_by``; equi-joins run on Acero's
vectorized hash join via ``Table.join`` (the same execution strategy the
reference gets from DataFusion, ref: crates/arkflow-plugin/src/processor/
sql.rs:112-129 and buffer/join.rs:111-118); window functions run on the
sort+segment executor in ``winfuncs.py``. Scalar-over-aggregate expressions
(``sum(x)/count(*)``) are handled by substituting computed aggregate columns
into the expression tree and re-evaluating on the aggregated table.

Queries outside this shape raise ``UnsupportedSql`` and the engine reroutes
them to the sqlite fallback.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from arkflow_tpu.batch import MessageBatch
from arkflow_tpu.errors import UnsupportedSql
from arkflow_tpu.sql import ast
from arkflow_tpu.sql.eval import Evaluator
from arkflow_tpu.sql.functions import NATIVE_AGGREGATES, as_array, has_function
from arkflow_tpu.sql.winfuncs import compute_window


def render(e: ast.Expr) -> str:
    """Stable display name for an unaliased expression column."""
    if isinstance(e, ast.Column):
        return e.name
    if isinstance(e, ast.Literal):
        return repr(e.value)
    if isinstance(e, ast.Func):
        inner = "*" if e.is_star else ", ".join(render(a) for a in e.args)
        d = "DISTINCT " if e.distinct else ""
        return f"{e.name}({d}{inner})"
    if isinstance(e, ast.WindowFunc):
        return render(e.func) + " over"
    if isinstance(e, ast.Binary):
        return f"{render(e.left)} {e.op} {render(e.right)}"
    if isinstance(e, ast.Unary):
        return f"{e.op} {render(e.operand)}"
    if isinstance(e, ast.Cast):
        return f"cast({render(e.operand)} as {e.type_name})"
    return type(e).__name__.lower()


def _find_aggregates(e: ast.Expr, out: list[ast.Func]) -> None:
    if isinstance(e, ast.WindowFunc):
        return  # its inner func is a window evaluation, not a group aggregate
    if isinstance(e, ast.Func) and (e.name in NATIVE_AGGREGATES or e.is_star and e.name == "count"):
        if e.name in NATIVE_AGGREGATES or e.is_star:
            out.append(e)
            return  # don't descend into aggregate args
    if isinstance(e, ast.Func) and not has_function(e.name) and not e.is_star:
        # unknown function: could be an aggregate UDF -> not natively plannable
        raise UnsupportedSql(f"unknown function {e.name!r} in native planner")
    for child in _children(e):
        _find_aggregates(child, out)


def _find_windows(e: ast.Expr, out: list[ast.WindowFunc]) -> None:
    if isinstance(e, ast.WindowFunc):
        if e not in out:
            out.append(e)
        return
    for child in _children(e):
        _find_windows(child, out)


def _children(e: ast.Expr) -> list[ast.Expr]:
    if isinstance(e, ast.Unary):
        return [e.operand]
    if isinstance(e, ast.Binary):
        return [e.left, e.right]
    if isinstance(e, ast.IsNull):
        return [e.operand]
    if isinstance(e, ast.InList):
        return [e.operand, *e.items]
    if isinstance(e, ast.Between):
        return [e.operand, e.low, e.high]
    if isinstance(e, ast.Func):
        return list(e.args)
    if isinstance(e, ast.Cast):
        return [e.operand]
    if isinstance(e, ast.WindowFunc):
        return [e.func, *e.partition_by, *[o.expr for o in e.order_by]]
    if isinstance(e, ast.Case):
        out = list(e.whens and [x for w in e.whens for x in w] or [])
        if e.operand is not None:
            out.append(e.operand)
        if e.otherwise is not None:
            out.append(e.otherwise)
        return out
    return []


def _substitute(e: ast.Expr, mapping: dict[ast.Expr, ast.Column]) -> ast.Expr:
    """Replace mapped subtrees (group keys / aggregates / windows) with
    column refs."""
    if e in mapping:
        return mapping[e]
    if isinstance(e, ast.Unary):
        return ast.Unary(e.op, _substitute(e.operand, mapping))
    if isinstance(e, ast.Binary):
        return ast.Binary(e.op, _substitute(e.left, mapping), _substitute(e.right, mapping))
    if isinstance(e, ast.IsNull):
        return ast.IsNull(_substitute(e.operand, mapping), e.negated)
    if isinstance(e, ast.InList):
        return ast.InList(_substitute(e.operand, mapping), tuple(_substitute(i, mapping) for i in e.items), e.negated)
    if isinstance(e, ast.Between):
        return ast.Between(_substitute(e.operand, mapping), _substitute(e.low, mapping), _substitute(e.high, mapping), e.negated)
    if isinstance(e, ast.Func):
        return ast.Func(e.name, tuple(_substitute(a, mapping) for a in e.args), e.distinct, e.is_star)
    if isinstance(e, ast.Cast):
        return ast.Cast(_substitute(e.operand, mapping), e.type_name)
    if isinstance(e, ast.Case):
        return ast.Case(
            _substitute(e.operand, mapping) if e.operand is not None else None,
            tuple((_substitute(c, mapping), _substitute(v, mapping)) for c, v in e.whens),
            _substitute(e.otherwise, mapping) if e.otherwise is not None else None,
        )
    return e


class _From:
    """Resolved FROM/JOIN clause: one batch with internal slot columns plus
    the visible-name -> slot mapping used to build Evaluators."""

    def __init__(self, rb: pa.RecordBatch, names: dict[str, str],
                 stars: list[tuple[str, str]],
                 alias_stars: dict[str, list[tuple[str, str]]]):
        self.rb = rb
        self.names = names            # bare + qualified visible name -> slot
        self.stars = stars            # ordered (display, slot) for bare *
        self.alias_stars = alias_stars  # alias -> [(display, slot)] for a.*

    @property
    def num_rows(self) -> int:
        return self.rb.num_rows

    def evaluator(self) -> Evaluator:
        idx = {nm: i for i, nm in enumerate(self.rb.schema.names)}
        cols = {name: self.rb.column(idx[slot]) for name, slot in self.names.items()}
        return Evaluator(cols, self.rb.num_rows)

    def filter(self, mask: pa.Array) -> None:
        self.rb = self.rb.filter(mask)

    def add_column(self, slot: str, arr: pa.Array) -> None:
        arrays = [*self.rb.columns, arr]
        names = [*self.rb.schema.names, slot]
        self.rb = pa.RecordBatch.from_arrays(arrays, names=names)
        self.names[slot] = slot

    def star_columns(self, table: Optional[str]) -> list[tuple[str, pa.Array]]:
        if table is None:
            pairs = self.stars
        else:
            pairs = self.alias_stars.get(table)
            if pairs is None:
                raise UnsupportedSql(f"unknown table alias {table!r} in *")
        idx = {nm: i for i, nm in enumerate(self.rb.schema.names)}
        return [(display, self.rb.column(idx[slot])) for display, slot in pairs]


def _lookup(tables: dict[str, MessageBatch], tref: ast.TableRef) -> pa.RecordBatch:
    batch = tables.get(tref.name)
    if batch is None:
        raise UnsupportedSql(f"unknown table {tref.name!r} (registered: {sorted(tables)})")
    return batch.record_batch


def _single_from(tables: dict[str, MessageBatch], tref: ast.TableRef) -> _From:
    rb = _lookup(tables, tref)
    alias = tref.alias or tref.name
    names: dict[str, str] = {}
    stars: list[tuple[str, str]] = []
    for c in rb.schema.names:
        names[c] = c
        names[f"{alias}.{c}"] = c
        stars.append((c, c))
    return _From(rb, names, stars, {alias: list(stars)})


# -- join resolution ---------------------------------------------------------


def _conjuncts(e: ast.Expr) -> list[ast.Expr]:
    if isinstance(e, ast.Binary) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _columns_of(e: ast.Expr, out: list[ast.Column]) -> None:
    if isinstance(e, ast.Column):
        out.append(e)
    for c in _children(e):
        _columns_of(c, out)


def _side_of(e: ast.Expr, left_names: dict[str, str], right_names: dict[str, str]) -> Optional[str]:
    """'left'/'right' if every column in e resolves to exactly one side."""
    cols: list[ast.Column] = []
    _columns_of(e, cols)
    if not cols:
        return None  # constant: ambiguous, treat as residual
    sides = set()
    for c in cols:
        key = f"{c.table}.{c.name}" if c.table else c.name
        in_l = key in left_names
        in_r = key in right_names
        if in_l and in_r:
            raise UnsupportedSql(f"ambiguous column {key!r} in JOIN condition")
        if in_l:
            sides.add("left")
        elif in_r:
            sides.add("right")
        else:
            raise UnsupportedSql(f"no such column {key!r} in JOIN condition")
    return sides.pop() if len(sides) == 1 else None


_JOIN_TYPES = {"inner": "inner", "left": "left outer",
               "right": "right outer", "full": "full outer"}


def _joined_from(sel: ast.Select, tables: dict[str, MessageBatch]) -> _From:
    """Fold the JOIN chain left-to-right through Acero's hash join."""
    refs = [(sel.table, None, None)] + [(j.table, j.on, j.kind) for j in sel.joins]

    cur: Optional[pa.Table] = None
    names: dict[str, str] = {}       # visible name -> slot
    bare_owner: dict[str, Optional[str]] = {}  # bare name -> slot | None=ambiguous
    stars: list[tuple[str, str]] = []
    alias_stars: dict[str, list[tuple[str, str]]] = {}

    for ti, (tref, on, kind) in enumerate(refs):
        rb = _lookup(tables, tref)
        alias = tref.alias or tref.name
        if alias in alias_stars:
            raise UnsupportedSql(f"duplicate table alias {alias!r}")
        slots = [f"__t{ti}c{j}" for j in range(rb.num_columns)]
        right = pa.table(list(rb.columns), names=slots) if rb.num_columns else pa.table({f"__t{ti}c0": pa.nulls(rb.num_rows)})
        right_names: dict[str, str] = {}
        for c, s in zip(rb.schema.names, slots):
            right_names[f"{alias}.{c}"] = s
            right_names.setdefault(c, s)
        pairs = [(c, s) for c, s in zip(rb.schema.names, slots)]
        alias_stars[alias] = pairs

        if cur is None:
            cur = right
        else:
            # ON sees prior tables' qualified names + unambiguous bare names
            left_vis = dict(names)
            for c, s in bare_owner.items():
                if s is not None and c not in left_vis:
                    left_vis[c] = s
            cur = _hash_join(cur, right, on, kind, left_vis, right_names)

        stars.extend(pairs)
        for name, s in right_names.items():
            if "." in name:
                names[name] = s
        for c in rb.schema.names:
            if c in bare_owner:
                bare_owner[c] = None  # ambiguous across tables
            else:
                bare_owner[c] = right_names[f"{alias}.{c}"]

    for c, s in bare_owner.items():
        if s is not None and c not in names:
            names[c] = s

    # residual (non-equi) conditions were applied inside _hash_join; the
    # accumulated Table becomes one RecordBatch for downstream stages
    rb_out = MessageBatch.from_table(cur).record_batch
    return _From(rb_out, names, stars, alias_stars)


def _hash_join(cur: pa.Table, right: pa.Table, on: Optional[ast.Expr],
               kind: str, left_names: dict[str, str],
               right_names: dict[str, str]) -> pa.Table:
    """One join step: split ON into equi-keys + residual, run Acero."""
    # visible names for the accumulated left side: every qualified name so
    # far, plus unambiguous bare names
    eqs: list[tuple[ast.Expr, ast.Expr]] = []
    residual: list[ast.Expr] = []
    if on is not None:
        for c in _conjuncts(on):
            if isinstance(c, ast.Binary) and c.op == "=":
                ls = _side_of(c.left, left_names, right_names)
                rs = _side_of(c.right, left_names, right_names)
                if ls == "left" and rs == "right":
                    eqs.append((c.left, c.right))
                    continue
                if ls == "right" and rs == "left":
                    eqs.append((c.right, c.left))
                    continue
            residual.append(c)
    if kind in ("left", "right", "full") and not eqs:
        raise UnsupportedSql(
            f"{kind.upper()} JOIN requires at least one equi-join key natively")
    # outer join with non-equi residual: Acero can't filter inside the join,
    # so run the INNER equi-join + residual, then re-append the rows whose
    # matches were all eliminated (null-extended) — standard outer semantics
    outer_residual = kind if (kind in ("left", "right", "full") and residual) else None
    if outer_residual in ("left", "full"):
        cur = cur.append_column(
            "__orid_l", pa.array(np.arange(cur.num_rows, dtype=np.int64)))
    if outer_residual in ("right", "full"):
        right = right.append_column(
            "__orid_r", pa.array(np.arange(right.num_rows, dtype=np.int64)))
    if residual and not eqs and kind != "cross":
        # non-equi inner join: cross product + filter
        kind = "cross"

    def _ev(tbl: pa.Table, nm: dict[str, str]) -> Evaluator:
        idx = {s: i for i, s in enumerate(tbl.schema.names)}
        cols = {name: tbl.column(idx[slot]) for name, slot in nm.items() if slot in idx}
        return Evaluator(cols, tbl.num_rows)

    lkeys, rkeys = [], []
    ltmp, rtmp = [], []
    if kind == "cross" or not eqs:
        # constant-key join = cross product
        cur = cur.append_column("__xk_l", pa.array([0] * cur.num_rows, pa.int8()))
        right = right.append_column("__xk_r", pa.array([0] * right.num_rows, pa.int8()))
        lkeys, rkeys = ["__xk_l"], ["__xk_r"]
        ltmp, rtmp = ["__xk_l"], ["__xk_r"]
        join_type = "inner"
    else:
        lev, rev = _ev(cur, left_names), _ev(right, right_names)
        for i, (le, re_) in enumerate(eqs):
            lv = as_array(lev.eval(le), cur.num_rows)
            rv = as_array(rev.eval(re_), right.num_rows)
            # align key types: acero rejects mismatched key types. Null-typed
            # keys (empty/all-None columns) can't cast — route to the sqlite
            # fallback instead of leaking ArrowNotImplementedError
            if pa.types.is_null(lv.type) or pa.types.is_null(rv.type):
                raise UnsupportedSql("join key column has null type")
            if lv.type != rv.type:
                common = pa.float64() if (pa.types.is_floating(lv.type) or pa.types.is_floating(rv.type)) else None
                try:
                    if common is None:
                        try:
                            rv = pc.cast(rv, lv.type)
                        except pa.ArrowInvalid:
                            lv = pc.cast(lv, rv.type)
                    else:
                        lv, rv = pc.cast(lv, common, safe=False), pc.cast(rv, common, safe=False)
                except (pa.ArrowInvalid, pa.ArrowNotImplementedError) as e:
                    raise UnsupportedSql(f"join key types incompatible: {e}")
            ln, rn = f"__jk{i}_l", f"__jk{i}_r"
            cur = cur.append_column(ln, lv)
            right = right.append_column(rn, rv)
            lkeys.append(ln)
            rkeys.append(rn)
            ltmp.append(ln)
            rtmp.append(rn)
        join_type = "inner" if outer_residual else _JOIN_TYPES[kind]

    joined = cur.join(right, keys=lkeys, right_keys=rkeys,
                      join_type=join_type, coalesce_keys=False)
    joined = joined.drop_columns([c for c in ltmp + rtmp if c in joined.schema.names])

    if residual:
        # bare names visible on BOTH sides are ambiguous: drop them so the
        # eval raises UnsupportedSql and the sqlite fallback surfaces the
        # standard "ambiguous column" error instead of silently picking a side
        both = dict(left_names)
        for name, slot in right_names.items():
            if "." not in name and name in both and both[name] != slot:
                del both[name]
                continue
            both[name] = slot
        ev = _ev(joined, both)
        mask = None
        for c in residual:
            m = as_array(ev.eval(c), joined.num_rows)
            if not pa.types.is_boolean(m.type):
                m = pc.cast(m, pa.bool_())
            mask = m if mask is None else pc.and_kleene(mask, m)
        joined = joined.filter(pc.fill_null(mask, False))
    if outer_residual:
        if outer_residual in ("left", "full"):
            joined = _append_unmatched(joined, cur, "__orid_l")
        if outer_residual in ("right", "full"):
            joined = _append_unmatched(joined, right, "__orid_r")
        joined = joined.drop_columns(
            [c for c in ("__orid_l", "__orid_r") if c in joined.schema.names])
    return joined


def _append_unmatched(joined: pa.Table, side: pa.Table, rid: str) -> pa.Table:
    """Null-extend ``side`` rows with no surviving match into ``joined``
    (the outer half of an outer join whose ON carries a residual)."""
    seen = pc.unique(joined.column(rid))
    keep = pc.invert(pc.is_in(side.column(rid), value_set=seen))
    miss = side.filter(pc.fill_null(keep, True))
    if miss.num_rows == 0:
        return joined
    cols = []
    for field in joined.schema:
        if field.name in miss.schema.names:
            col = miss.column(field.name)
            if col.type != field.type:
                col = pc.cast(col, field.type)
            cols.append(col)
        else:
            cols.append(pa.nulls(miss.num_rows, field.type))
    return pa.concat_tables(
        [joined, pa.table(cols, names=joined.schema.names)])


# -- select execution --------------------------------------------------------


def execute_select(sel: ast.Select, tables: dict[str, MessageBatch]) -> MessageBatch:
    """Run a parsed SELECT natively; raise UnsupportedSql otherwise."""
    if sel.table is None:
        # SELECT <exprs> without FROM: single-row evaluation
        ev = Evaluator({}, 1)
        arrays, names = [], []
        for item in sel.items:
            if isinstance(item.expr, ast.Star):
                raise UnsupportedSql("* without FROM")
            v = ev.eval(item.expr)
            arrays.append(as_array(v, 1))
            names.append(item.alias or render(item.expr))
        return MessageBatch(pa.RecordBatch.from_arrays(arrays, names=names))

    src = _joined_from(sel, tables) if sel.joins else _single_from(tables, sel.table)

    # WHERE
    if sel.where is not None:
        wins_in_where: list[ast.WindowFunc] = []
        _find_windows(sel.where, wins_in_where)
        if wins_in_where:
            raise UnsupportedSql("window functions are not allowed in WHERE")
        ev = src.evaluator()
        mask = as_array(ev.eval(sel.where), src.num_rows)
        if not pa.types.is_boolean(mask.type):
            mask = pc.cast(mask, pa.bool_())
        src.filter(mask)

    # aggregate / window discovery
    aggs: list[ast.Func] = []
    wins: list[ast.WindowFunc] = []
    for item in sel.items:
        if not isinstance(item.expr, ast.Star):
            _find_aggregates(item.expr, aggs)
            _find_windows(item.expr, wins)
    if sel.having is not None:
        _find_aggregates(sel.having, aggs)
    for oi in sel.order_by:
        _find_windows(oi.expr, wins)

    win_mapping: dict[ast.Expr, ast.Column] = {}
    if wins:
        if sel.group_by or aggs:
            raise UnsupportedSql(
                "window functions mixed with GROUP BY/aggregates not supported natively")
        ev = src.evaluator()
        for i, w in enumerate(wins):
            arr = compute_window(w, ev, src.num_rows)
            src.add_column(f"__win_{i}", arr)
            win_mapping[w] = ast.Column(f"__win_{i}")

    agg_env: Optional[tuple[pa.RecordBatch, dict]] = None
    if sel.group_by or aggs:
        out, agg_env = _execute_aggregate(sel, src, aggs)
    else:
        out = _execute_projection(sel, src, win_mapping)

    # DISTINCT
    if sel.distinct:
        t = pa.Table.from_batches([out])
        t = t.group_by(t.schema.names, use_threads=False).aggregate([])
        out = MessageBatch.from_table(t).record_batch

    # ORDER BY
    if sel.order_by:
        out = _order(out, sel, src, win_mapping, agg_env)

    # LIMIT/OFFSET
    if sel.offset is not None:
        out = out.slice(sel.offset)
    if sel.limit is not None:
        out = out.slice(0, sel.limit)
    return MessageBatch(out)


def _execute_projection(sel: ast.Select, src: _From,
                        win_mapping: dict[ast.Expr, ast.Column]) -> pa.RecordBatch:
    ev = src.evaluator()
    arrays: list[pa.Array] = []
    names: list[str] = []
    for item in sel.items:
        if isinstance(item.expr, ast.Star):
            for display, arr in src.star_columns(item.expr.table):
                arrays.append(arr)
                names.append(display)
            continue
        e = _substitute(item.expr, win_mapping) if win_mapping else item.expr
        v = ev.eval(e)
        arrays.append(as_array(v, src.num_rows))
        names.append(item.alias or render(item.expr))
    return pa.RecordBatch.from_arrays(arrays, names=names)


_DISTINCT_AGGS = {"count": "count_distinct"}


def _execute_aggregate(sel: ast.Select, src: _From,
                       aggs: list[ast.Func]) -> tuple[pa.RecordBatch, tuple]:
    ev = src.evaluator()
    n = src.num_rows

    # Deduplicate aggregates structurally.
    uniq: list[ast.Func] = []
    for a in aggs:
        if a not in uniq:
            uniq.append(a)

    # Build the pre-aggregation table: key columns + aggregate input columns.
    key_names, key_arrays = [], []
    mapping: dict[ast.Expr, ast.Column] = {}
    for i, g in enumerate(sel.group_by):
        kn = f"__key_{i}"
        key_names.append(kn)
        key_arrays.append(as_array(ev.eval(g), n))
        mapping[g] = ast.Column(kn)

    agg_specs = []  # (input_col_name_or_[], kernel, output_name)
    in_names, in_arrays = [], []
    for i, a in enumerate(uniq):
        out_name = f"__agg_{i}"
        if a.is_star:  # count(*)
            agg_specs.append(([], "count_all", out_name))
        else:
            if len(a.args) != 1:
                raise UnsupportedSql(f"aggregate {a.name} takes exactly one argument natively")
            kernel = NATIVE_AGGREGATES[a.name]
            if a.distinct:
                kernel = _DISTINCT_AGGS.get(a.name)
                if kernel is None:
                    raise UnsupportedSql(f"DISTINCT {a.name} not supported natively")
            col = f"__in_{i}"
            in_names.append(col)
            in_arrays.append(as_array(ev.eval(a.args[0]), n))
            agg_specs.append((col, kernel, out_name))
        mapping[a] = ast.Column(f"__agg_{i}")

    pre = pa.table(dict(zip(key_names + in_names, key_arrays + in_arrays))) if (key_names or in_names) else pa.table({"__dummy__": pa.nulls(n)})

    grouped = pre.group_by(key_names, use_threads=False).aggregate(
        [(c, k) for c, k, _ in agg_specs]
    )
    # pyarrow names results "<col>_<kernel>"; rename to our __agg_i slots.
    rename: dict[str, str] = {}
    for c, k, out_name in agg_specs:
        produced = f"{c}_{k}" if c != [] else k  # ([], "count_all") -> "count_all"
        rename[produced] = out_name
    grouped = grouped.rename_columns([rename.get(nm, nm) for nm in grouped.schema.names])
    agg_rb = MessageBatch.from_table(grouped).record_batch

    # HAVING on the aggregated table.
    if sel.having is not None:
        hev = Evaluator.for_batch(agg_rb)
        mask = as_array(hev.eval(_substitute(sel.having, mapping)), agg_rb.num_rows)
        if not pa.types.is_boolean(mask.type):
            mask = pc.cast(mask, pa.bool_())
        agg_rb = agg_rb.filter(mask)

    # Final projection over key/agg columns.
    fev = Evaluator.for_batch(agg_rb)
    arrays, names = [], []
    for item in sel.items:
        if isinstance(item.expr, ast.Star):
            raise UnsupportedSql("* not valid in aggregate query")
        sub = _substitute(item.expr, mapping)
        _assert_resolved(sub, set(agg_rb.schema.names))
        arrays.append(as_array(fev.eval(sub), agg_rb.num_rows))
        names.append(item.alias or render(item.expr))
    return pa.RecordBatch.from_arrays(arrays, names=names), (agg_rb, mapping)


def _assert_resolved(e: ast.Expr, available: set[str]) -> None:
    """Every column in a post-aggregation expression must be a key or agg slot."""
    if isinstance(e, ast.Column) and e.name not in available:
        raise UnsupportedSql(
            f"column {e.name!r} must appear in GROUP BY or inside an aggregate"
        )
    for c in _children(e):
        _assert_resolved(c, available)


def _order(out: pa.RecordBatch, sel: ast.Select, src: _From,
           win_mapping: dict[ast.Expr, ast.Column],
           agg_env: Optional[tuple] = None) -> pa.RecordBatch:
    sort_cols: list[tuple[str, str]] = []
    extra: dict[str, pa.Array] = {}
    for i, oi in enumerate(sel.order_by):
        direction = "ascending" if oi.asc else "descending"
        e = _substitute(oi.expr, win_mapping) if win_mapping else oi.expr
        if isinstance(e, ast.Literal) and isinstance(e.value, int):
            idx = e.value - 1
            if not (0 <= idx < out.num_columns):
                raise UnsupportedSql(f"ORDER BY position {e.value} out of range")
            sort_cols.append((out.schema.names[idx], direction))
            continue
        if isinstance(e, ast.Column) and e.table is None and e.name in out.schema.names:
            sort_cols.append((e.name, direction))
            continue
        # expression over output (aliases); else over the aggregated rows
        # (group keys/aggregates substituted in); else over the source rows
        try:
            v = as_array(Evaluator.for_batch(out).eval(e), out.num_rows)
        except UnsupportedSql:
            if agg_env is not None:
                agg_rb, amap = agg_env
                if agg_rb.num_rows != out.num_rows:
                    raise UnsupportedSql("ORDER BY expression not resolvable against output")
                sub = _substitute(e, amap)
                _assert_resolved(sub, set(agg_rb.schema.names))
                v = as_array(Evaluator.for_batch(agg_rb).eval(sub), out.num_rows)
            else:
                if src.num_rows != out.num_rows:
                    raise UnsupportedSql("ORDER BY expression not resolvable against output")
                v = as_array(src.evaluator().eval(e), out.num_rows)
        name = f"__sort_{i}"
        extra[name] = v
        sort_cols.append((name, direction))
    colmap: dict[str, pa.Array] = {}
    for nm, arr in zip(out.schema.names, out.columns):
        colmap.setdefault(nm, arr)
    colmap.update(extra)
    key_t = pa.table({c: colmap[c] for c, _ in sort_cols})
    indices = pc.sort_indices(key_t, sort_keys=sort_cols)
    return out.take(indices)
