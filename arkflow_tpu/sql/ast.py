"""SQL AST nodes."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


class Expr:
    __slots__ = ()


@dataclass(frozen=True)
class Literal(Expr):
    value: object  # int | float | str | bool | None


@dataclass(frozen=True)
class Column(Expr):
    name: str
    table: Optional[str] = None


@dataclass(frozen=True)
class Star(Expr):
    table: Optional[str] = None


@dataclass(frozen=True)
class Unary(Expr):
    op: str  # 'not' | '-' | '+'
    operand: Expr


@dataclass(frozen=True)
class Binary(Expr):
    op: str  # and or = != < <= > >= + - * / % || like ilike
    left: Expr
    right: Expr


@dataclass(frozen=True)
class IsNull(Expr):
    operand: Expr
    negated: bool = False


@dataclass(frozen=True)
class InList(Expr):
    operand: Expr
    items: tuple[Expr, ...] = ()
    negated: bool = False


@dataclass(frozen=True)
class Between(Expr):
    operand: Expr
    low: Expr
    high: Expr
    negated: bool = False


@dataclass(frozen=True)
class Func(Expr):
    name: str  # lowercase
    args: tuple[Expr, ...] = ()
    distinct: bool = False
    is_star: bool = False  # count(*)


@dataclass(frozen=True)
class Cast(Expr):
    operand: Expr
    type_name: str  # lowercase sql type


@dataclass(frozen=True)
class WindowFunc(Expr):
    """``func(...) OVER (PARTITION BY ... ORDER BY ...)``.

    Only the default frame is representable (RANGE UNBOUNDED PRECEDING..
    CURRENT ROW when ordered, the whole partition otherwise); explicit
    frames raise UnsupportedSql at parse."""

    func: "Func"
    partition_by: tuple[Expr, ...] = ()
    order_by: tuple["OrderItem", ...] = ()


@dataclass(frozen=True)
class Case(Expr):
    operand: Optional[Expr]  # CASE x WHEN ... vs CASE WHEN ...
    whens: tuple[tuple[Expr, Expr], ...] = ()
    otherwise: Optional[Expr] = None


@dataclass(frozen=True)
class SelectItem:
    expr: Expr
    alias: Optional[str] = None


@dataclass(frozen=True)
class TableRef:
    name: str
    alias: Optional[str] = None


@dataclass(frozen=True)
class Join:
    kind: str  # inner | left | right | full | cross
    table: TableRef
    on: Optional[Expr] = None


@dataclass(frozen=True)
class OrderItem:
    expr: Expr
    asc: bool = True


@dataclass
class Select:
    items: list[SelectItem] = field(default_factory=list)
    table: Optional[TableRef] = None
    joins: list[Join] = field(default_factory=list)
    where: Optional[Expr] = None
    group_by: list[Expr] = field(default_factory=list)
    having: Optional[Expr] = None
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    distinct: bool = False
