"""VRL (Vector Remap Language) front-end, compiled onto the columnar engine.

The reference embeds the real VRL runtime and resolves programs row by row
(ref: crates/arkflow-plugin/src/processor/vrl.rs:42-115). A row interpreter
would throw away columnar execution, so this front-end *compiles* the common
VRL surface into a short plan of vectorized steps over Arrow batches — the
same expression engine that powers WHERE clauses and the remap processor.
A reference config with a ``vrl:`` block runs unmodified when its program
stays inside the supported subset; anything else fails at build time with a
clear error naming the unsupported construct.

Supported surface:

- field assignment ``.out = expr`` (top-level and dotted display names)
- local variables ``tmp = expr`` (bound at assignment time: materialized as
  hidden columns so later mutation of their source fields cannot change them)
- ``del(.field)``
- ``if cond { ... } else if ... { ... } else { ... }`` where branches hold
  assignments (compiled to masked columnar assignments against a branch-entry
  mask snapshot) or ``abort`` (compiled to a row filter, VRL's
  drop-on-abort semantics)
- operators ``== != < <= > >= && || ! + - * / % ?? ``, literals, parens,
  ``r'...'`` regex literals
- the fallible-call forms ``f!(...)`` and ``f(...) ?? default`` (every
  parser here yields NULL on failure, so ``??`` is ``coalesce``)
- object-returning parsers used with a path: ``parse_json!(.m).a.b``,
  ``parse_url!(.u).host``, ``parse_key_value!(.l).level``,
  ``parse_regex!(.x, r'(?P<g>..)').g``
- a stdlib mapped onto ``sql/functions.py`` (to_int/to_float/to_string,
  upcase/downcase/trim/replace/length/contains/starts_with/ends_with/
  slice/truncate, round/abs/floor/ceil, md5/sha2, match,
  parse_timestamp/format_timestamp, now, exists/is_null, coalesce)
- the list/object tier: ``split`` (Arrow list column), ``join``, postfix
  indexing ``split(.x, ",")[0]`` (negative from the end, out-of-range ->
  null), ``merge`` (shallow JSON object merge, right wins) and
  ``encode_json`` (ref vrl.rs:42-115 runs these in the embedded runtime)
- whole-event assignment ``. = parse_json!(.col)`` (expands the object into
  typed columns, replacing the event; ``__meta_*`` survives) and
  ``parse_syslog!(.line).part`` (RFC 5424 + legacy 3164)
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional

import pyarrow as pa
import pyarrow.compute as pc

from arkflow_tpu.batch import MessageBatch
from arkflow_tpu.errors import ConfigError
from arkflow_tpu.sql import ast
from arkflow_tpu.sql.eval import Evaluator
from arkflow_tpu.sql.functions import as_array


class VrlCompileError(ConfigError):
    """VRL program outside the supported subset (build-time)."""


# ---------------------------------------------------------------------------
# lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>[ \t]+)
  | (?P<comment>\#[^\n]*)
  | (?P<nl>[\r\n]+)
  | (?P<regex>r'(?:[^'\\]|\\.)*')
  | (?P<string>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
  | (?P<number>\d+\.\d+|\d+)
  | (?P<path>\.(?:[A-Za-z_][A-Za-z0-9_]*(?:\.[A-Za-z_][A-Za-z0-9_]*)*)?)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*!?)
  | (?P<op>\?\?|==|!=|<=|>=|&&|\|\||[-+*/%<>=!(){},;:\[\]])
    """,
    re.VERBOSE,
)


@dataclass
class _Tok:
    kind: str  # nl string regex number path ident op eof
    value: str
    pos: int


def _lex(src: str) -> list[_Tok]:
    toks: list[_Tok] = []
    i = 0
    while i < len(src):
        m = _TOKEN_RE.match(src, i)
        if m is None:
            raise VrlCompileError(f"vrl: unexpected character {src[i]!r} at {i}")
        kind = m.lastgroup
        i = m.end()
        if kind in ("ws", "comment"):
            continue
        toks.append(_Tok(kind, m.group(), m.start()))
    toks.append(_Tok("eof", "", len(src)))
    return toks


def _unquote(s: str) -> str:
    body = s[1:-1]
    return re.sub(r"\\(.)", lambda m: {"n": "\n", "t": "\t", "r": "\r"}.get(
        m.group(1), m.group(1)), body)


# ---------------------------------------------------------------------------
# compiled plan
# ---------------------------------------------------------------------------

# steps: ("mask", slot, cond_expr, parent_slot|None)   — branch-entry snapshot
#      | ("maskelse", slot, then_slot, parent_slot|None) — parent AND NOT then
#      | ("assign", col, expr) | ("cassign", col, slot, value)
#      | ("del", col) | ("filter", slot|None)          — abort; None = all rows
#
# Branch conditions are evaluated ONCE into a numbered mask slot when the
# if-statement is reached (VRL row semantics: a row's branch choice is fixed
# before the branch body mutates anything). Body steps then reference the
# slot instead of re-evaluating the condition against the mutated batch —
# re-evaluation silently no-op'd later statements whenever a branch assigned
# to a column its own condition read (advisor r3, high).
Step = tuple

# hidden-column prefix for materialized local variables (stripped from the
# output batch). Locals bind their VALUE at assignment time (VRL semantics);
# textual inlining would re-read mutated source columns (advisor r3, low).
_LOCAL_PREFIX = "__vrl_"


# VRL function name -> (sql function name, arity range)
_FN = {
    "to_int": "parse_int", "int": "parse_int",
    "to_float": "parse_float", "float": "parse_float",
    "to_string": "to_string", "string": "to_string",
    "upcase": "upper", "downcase": "lower",
    "trim": "trim", "strip_whitespace": "trim",
    "replace": "replace", "length": "length", "strlen": "length",
    "round": "round", "abs": "abs", "floor": "floor", "ceil": "ceil",
    "md5": "md5", "sha2": "sha256", "sha256": "sha256",
    "match": "regex_match",
    "parse_timestamp": "parse_timestamp",
    "format_timestamp": "format_timestamp",
    "parse_int": "parse_int", "parse_float": "parse_float",
    "starts_with": "starts_with", "ends_with": "ends_with",
    "now": "now", "coalesce": "coalesce",
    "split_part": "split_part",
    # list/object tier: Arrow list columns + row-wise JSON (functions.py)
    "split": "split", "join": "join",
    "merge": "merge", "encode_json": "encode_json",
}

# object-returning parsers: path access becomes an extra key argument
_OBJECT_FNS = {"parse_json", "parse_url", "parse_key_value", "parse_regex",
               "parse_syslog"}

# every hint from rounds 1-4 has since become a real implementation; kept
# for future genuinely non-columnar constructs
_UNSUPPORTED_HINTS: dict[str, str] = {}


class _Parser:
    def __init__(self, src: str):
        self.toks = _lex(src)
        self.i = 0
        self._mask_slots = 0

    def _new_slot(self) -> int:
        self._mask_slots += 1
        return self._mask_slots - 1

    def peek(self, skip_nl: bool = True) -> _Tok:
        j = self.i
        while skip_nl and self.toks[j].kind == "nl":
            j += 1
        return self.toks[j]

    def next(self, skip_nl: bool = True) -> _Tok:
        while skip_nl and self.toks[self.i].kind == "nl":
            self.i += 1
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_op(self, *ops: str) -> Optional[_Tok]:
        t = self.peek()
        if t.kind == "op" and t.value in ops:
            return self.next()
        return None

    def expect_op(self, op: str) -> _Tok:
        t = self.next()
        if not (t.kind == "op" and t.value == op):
            raise VrlCompileError(f"vrl: expected {op!r} at {t.pos}, got {t.value!r}")
        return t

    # -- program -----------------------------------------------------------

    def parse_program(self) -> list[Step]:
        steps: list[Step] = []
        env: dict[str, ast.Expr] = {}
        while self.peek().kind != "eof":
            if self.accept_op(";"):
                continue
            # a bare trailing '.' (VRL's "return the event") is a no-op here
            t = self.peek()
            if t.kind == "path" and t.value == ".":
                nxt = self.toks[self._index_after(t)]
                if nxt.kind in ("eof", "nl") or (nxt.kind == "op" and nxt.value == ";"):
                    self.next()
                    continue
            steps.extend(self._statement(env))
        return steps

    def _index_after(self, tok: _Tok) -> int:
        for j in range(self.i, len(self.toks)):
            if self.toks[j] is tok:
                return j + 1
        return len(self.toks) - 1

    def _statement(self, env: dict[str, ast.Expr],
                   cond_slot: Optional[int] = None) -> list[Step]:
        t = self.peek()
        if t.kind == "ident" and t.value == "if":
            return self._if_statement(env, cond_slot)
        if t.kind == "ident" and t.value == "abort":
            self.next()
            return [("filter", cond_slot)]
        if t.kind == "ident" and t.value in ("del", "del!"):
            self.next()
            self.expect_op("(")
            p = self.next()
            if p.kind != "path" or p.value == ".":
                raise VrlCompileError(f"vrl: del() needs a field path at {p.pos}")
            self.expect_op(")")
            if cond_slot is not None:
                raise VrlCompileError(
                    "vrl: del() inside if-branches is not supported; "
                    "assign null instead")
            return [("del", p.value[1:])]
        if t.kind == "path":
            self.next()
            if t.value == ".":
                # whole-event assignment: `. = parse_json!(.col)` replaces
                # the event with the parsed object's columns (metadata and
                # locals survive, like VRL's separately-held metadata)
                self.expect_op("=")
                fn = self.next()
                if not (fn.kind == "ident" and fn.value.rstrip("!") == "parse_json"):
                    raise VrlCompileError(
                        "vrl: whole-event assignment supports "
                        "'. = parse_json!(<expr>)' (other object sources "
                        "have no columnar form)")
                self.expect_op("(")
                inner = self._expr(env)
                self.expect_op(")")
                if cond_slot is not None:
                    raise VrlCompileError(
                        "vrl: '. = parse_json!(..)' inside if-branches is "
                        "not supported (the event schema must not depend on "
                        "the row)")
                return [("expand", inner)]
            # '.out, err = expr': VRL's error-capture tuple. Fallible ops
            # here yield NULL instead of an error value, so err binds null.
            err_var = None
            if self.accept_op(","):
                ev_tok = self.next()
                if ev_tok.kind != "ident":
                    raise VrlCompileError(
                        f"vrl: expected error variable after ',' at {ev_tok.pos}")
                err_var = ev_tok.value
            self.expect_op("=")
            e = self._expr(env)
            if err_var is not None:
                env[err_var] = ast.Literal(None)
            col = t.value[1:]
            if cond_slot is not None:
                return [("cassign", col, cond_slot, e)]
            return [("assign", col, e)]
        if t.kind == "ident":
            # local variable binding: bind the VALUE now by materializing a
            # hidden column (literals stay inline — nothing can mutate them)
            save = self.i
            name = self.next()
            if self.accept_op("="):
                if self.peek().kind == "op" and self.peek().value == "=":
                    raise VrlCompileError(f"vrl: '==' at statement level at {name.pos}")
                e = self._expr(env)
                # the literal inline shortcut is only sound at top level: a
                # literal bound inside a branch must land on the branch's rows
                # only, so it rides the masked cassign path (advisor r4, med)
                if isinstance(e, ast.Literal) and cond_slot is None:
                    env[name.value] = e
                    return []
                hidden = _LOCAL_PREFIX + name.value
                steps: list[Step] = []
                if cond_slot is not None:
                    # non-matching rows must keep the pre-branch value, so the
                    # prior binding is materialized into the hidden column
                    # before the masked write (unbound-before -> null, which
                    # is what cassign's missing-column base already yields)
                    prior = env.get(name.value)
                    if prior is not None and not (
                            isinstance(prior, ast.Column) and prior.name == hidden):
                        steps.append(("assign", hidden, prior))
                    steps.append(("cassign", hidden, cond_slot, e))
                else:
                    steps.append(("assign", hidden, e))
                env[name.value] = ast.Column(hidden)
                return steps
            self.i = save
        raise VrlCompileError(f"vrl: unsupported statement at {t.pos}: {t.value!r}")

    def _if_statement(self, env: dict[str, ast.Expr],
                      parent_slot: Optional[int]) -> list[Step]:
        self.next()  # 'if'
        cond = self._expr(env)
        # snapshot BOTH polarities before any body step runs: a then-branch
        # that assigns to a condition column must not flip rows into/out of
        # its own else-branch
        then_slot = self._new_slot()
        steps: list[Step] = [("mask", then_slot, cond, parent_slot)]
        else_slot: Optional[int] = None
        body = self._block(env, then_slot)
        if self.peek().kind == "ident" and self.peek().value == "else":
            else_slot = self._new_slot()
            # else = parent AND NOT then-mask (not `not cond`): the then-mask
            # is null-filled, so rows whose condition is null fall into else,
            # matching VRL's null-is-false predicate (advisor r4, low)
            steps.append(("maskelse", else_slot, then_slot, parent_slot))
        steps.extend(body)
        if else_slot is not None:
            self.next()  # 'else'
            if self.peek().kind == "ident" and self.peek().value == "if":
                steps.extend(self._if_statement(env, else_slot))
            else:
                steps.extend(self._block(env, else_slot))
        return steps

    def _block(self, env: dict[str, ast.Expr], cond_slot: int) -> list[Step]:
        self.expect_op("{")
        steps: list[Step] = []
        while not self.accept_op("}"):
            if self.peek().kind == "eof":
                raise VrlCompileError("vrl: unterminated block")
            steps.extend(self._statement(env, cond_slot))
        return steps

    # -- expressions -------------------------------------------------------

    def _expr(self, env) -> ast.Expr:
        return self._coalesce(env)

    def _coalesce(self, env) -> ast.Expr:
        left = self._or(env)
        while self.accept_op("??"):
            left = ast.Func("coalesce", (left, self._or(env)))
        return left

    def _or(self, env) -> ast.Expr:
        left = self._and(env)
        while self.accept_op("||"):
            left = ast.Binary("or", left, self._and(env))
        return left

    def _and(self, env) -> ast.Expr:
        left = self._not(env)
        while self.accept_op("&&"):
            left = ast.Binary("and", left, self._not(env))
        return left

    def _not(self, env) -> ast.Expr:
        if self.accept_op("!"):
            return ast.Unary("not", self._not(env))
        return self._comparison(env)

    def _comparison(self, env) -> ast.Expr:
        left = self._additive(env)
        t = self.peek()
        if t.kind == "op" and t.value in ("==", "!=", "<", "<=", ">", ">="):
            self.next()
            op = "=" if t.value == "==" else t.value
            return ast.Binary(op, left, self._additive(env))
        return left

    def _additive(self, env) -> ast.Expr:
        left = self._mult(env)
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("+", "-"):
                self.next()
                left = ast.Binary(t.value, left, self._mult(env))
            else:
                return left

    def _mult(self, env) -> ast.Expr:
        left = self._unary(env)
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("*", "/", "%"):
                self.next()
                left = ast.Binary(t.value, left, self._unary(env))
            else:
                return left

    def _unary(self, env) -> ast.Expr:
        if self.accept_op("-"):
            e = self._unary(env)
            if isinstance(e, ast.Literal) and isinstance(e.value, (int, float)):
                return ast.Literal(-e.value)
            return ast.Unary("-", e)
        return self._primary(env)

    def _primary(self, env) -> ast.Expr:
        """An atom plus any postfix ``[i]`` list indexing (VRL's array
        access; 0-based, negative from the end, out-of-range -> null)."""
        e = self._atom(env)
        while self.accept_op("["):
            neg = self.accept_op("-") is not None
            t = self.next()
            if t.kind != "number" or "." in t.value:
                raise VrlCompileError(
                    f"vrl: list index must be an integer literal at {t.pos}")
            self.expect_op("]")
            idx = -int(t.value) if neg else int(t.value)
            e = ast.Func("list_get", (e, ast.Literal(idx)))
        return e

    def _atom(self, env) -> ast.Expr:
        t = self.next()
        if t.kind == "number":
            return ast.Literal(float(t.value) if "." in t.value else int(t.value))
        if t.kind == "string":
            return ast.Literal(_unquote(t.value))
        if t.kind == "regex":
            return ast.Literal(t.value[2:-1])  # r'...' -> pattern text
        if t.kind == "path":
            if t.value == ".":
                raise VrlCompileError(
                    "vrl: whole-event '.' is only meaningful row-wise; "
                    "reference a field like .message")
            return ast.Column(t.value[1:])
        if t.kind == "ident":
            name = t.value
            if name in ("true", "false"):
                return ast.Literal(name == "true")
            if name == "null":
                return ast.Literal(None)
            if name == "if":
                return self._if_expression(env)
            if self.peek(skip_nl=False).kind == "op" and self.peek(skip_nl=False).value == "(":
                return self._call(name, env)
            if name.rstrip("!") in env:
                return env[name.rstrip("!")]
            raise VrlCompileError(
                f"vrl: unknown identifier {name!r} at {t.pos} "
                "(fields are referenced as .name)")
        if t.kind == "op" and t.value == "(":
            e = self._expr(env)
            self.expect_op(")")
            return e
        raise VrlCompileError(f"vrl: unexpected token {t.value!r} at {t.pos}")

    def _if_expression(self, env) -> ast.Expr:
        """``if cond { a } else { b }`` as a value -> CASE WHEN."""
        cond = self._expr(env)
        self.expect_op("{")
        then_v = self._expr(env)
        self.expect_op("}")
        otherwise = None
        if self.peek().kind == "ident" and self.peek().value == "else":
            self.next()
            if self.peek().kind == "ident" and self.peek().value == "if":
                self.next()
                otherwise = self._if_expression(env)
            else:
                self.expect_op("{")
                otherwise = self._expr(env)
                self.expect_op("}")
        return ast.Case(None, ((cond, then_v),), otherwise)

    def _call(self, name: str, env) -> ast.Expr:
        fallible = name.endswith("!")
        base = name.rstrip("!")
        self.expect_op("(")
        args: list[ast.Expr] = []
        named: dict[str, ast.Expr] = {}
        while not self.accept_op(")"):
            if args or named:
                self.expect_op(",")
            t = self.peek()
            save = self.i
            if t.kind == "ident":
                nm = self.next()
                if self.accept_op(":"):
                    named[nm.value] = self._expr(env)
                    continue
                self.i = save
            args.append(self._expr(env))
        return self._lower_call(base, args, named, fallible)

    def _lower_call(self, base: str, args: list[ast.Expr],
                    named: dict[str, ast.Expr], fallible: bool) -> ast.Expr:
        # named args map positionally for the functions that take them
        if base == "parse_timestamp" and "format" in named:
            args = args + [named.pop("format")]
        if base in ("replace", "round", "truncate", "slice") and named:
            for k in list(named):
                args.append(named.pop(k))
        if named:
            raise VrlCompileError(
                f"vrl: named arguments {sorted(named)} for {base}() not supported")

        if base in _OBJECT_FNS:
            return self._object_access(base, args)
        if base == "exists":
            if len(args) != 1:
                raise VrlCompileError("vrl: exists() takes one field")
            return ast.IsNull(args[0], negated=True)
        if base == "is_null":
            return ast.IsNull(args[0])
        if base == "contains":
            if len(args) != 2:
                raise VrlCompileError("vrl: contains(haystack, needle)")
            return ast.Binary(">", ast.Func("strpos", tuple(args)), ast.Literal(0))
        if base == "slice":
            # slice(x, start[, end]) 0-based half-open -> substr 1-based len
            if len(args) == 2:
                return ast.Func("substr", (args[0], ast.Binary("+", args[1], ast.Literal(1))))
            if len(args) == 3:
                return ast.Func("substr", (
                    args[0], ast.Binary("+", args[1], ast.Literal(1)),
                    ast.Binary("-", args[2], args[1])))
            raise VrlCompileError("vrl: slice(x, start[, end])")
        if base == "truncate":
            if len(args) != 2:
                raise VrlCompileError("vrl: truncate(x, limit)")
            return ast.Func("substr", (args[0], ast.Literal(1), args[1]))
        mapped = _FN.get(base)
        if mapped is None:
            hint = _UNSUPPORTED_HINTS.get(base)
            raise VrlCompileError(
                f"vrl: function {base!r} is not in the supported subset"
                + (f" ({hint})" if hint else "")
                + f"; supported: {', '.join(sorted(set(_FN) | _OBJECT_FNS | {'exists', 'is_null', 'contains', 'slice', 'truncate', 'del'}))}")
        return ast.Func(mapped, tuple(args))

    def _object_access(self, base: str, args: list[ast.Expr]) -> ast.Expr:
        """parse_json!(.m).a.b / parse_url!(.u).host / parse_regex!(..).name —
        the trailing path becomes the key/part/group argument."""
        t = self.peek(skip_nl=False)
        if not (t.kind == "path" and t.value != "."):
            raise VrlCompileError(
                f"vrl: {base}() yields an object; access a field from it "
                f"(e.g. {base}!(.x).field) — whole-object assignment has no "
                "columnar form")
        self.next(skip_nl=False)
        key = t.value[1:]
        if base == "parse_json":
            # dynamic variant: VRL values keep their JSON type; the SQL
            # json_get stays always-string for schema stability
            return ast.Func("json_get_dyn", (args[0], ast.Literal(key)))
        if base == "parse_url":
            return ast.Func("parse_url", (args[0], ast.Literal(key)))
        if base == "parse_key_value":
            return ast.Func("parse_key_value", (args[0], ast.Literal(key), *args[1:]))
        if base == "parse_syslog":
            return ast.Func("parse_syslog", (args[0], ast.Literal(key)))
        if base == "parse_regex":
            if len(args) != 2:
                raise VrlCompileError("vrl: parse_regex(x, r'pattern').group")
            return ast.Func("regex_extract", (args[0], args[1], ast.Literal(key)))
        raise VrlCompileError(f"vrl: unhandled object parser {base}")


def compile_vrl(statement: str) -> list[Step]:
    """VRL source -> vectorized step plan. Raises VrlCompileError outside the
    supported subset (build-time, like the reference's compile at vrl.rs:109)."""
    return _Parser(statement).parse_program()


def apply_vrl(batch: MessageBatch, steps: list[Step]) -> MessageBatch:
    """Run a compiled plan over one batch."""
    rb = batch.record_batch
    masks: dict[int, pa.Array] = {}
    for step in steps:
        n = rb.num_rows
        ev = Evaluator.for_batch(rb)
        kind = step[0]
        if kind == "mask":
            _, slot, cond, parent = step
            m = pc.fill_null(_bool(ev.eval(cond), n), False)
            if parent is not None:
                m = pc.and_(m, masks[parent])
            masks[slot] = m
        elif kind == "maskelse":
            _, slot, then_slot, parent = step
            m = pc.invert(masks[then_slot])
            if parent is not None:
                m = pc.and_(m, masks[parent])
            masks[slot] = m
        elif kind == "assign":
            _, col, e = step
            rb = _set_column(rb, col, as_array(ev.eval(e), n))
        elif kind == "cassign":
            _, col, slot, e = step
            mask = masks[slot]
            val = as_array(ev.eval(e), n)
            names = rb.schema.names
            if col in names:
                base = rb.column(names.index(col))
                if base.type != val.type:
                    if pa.types.is_null(base.type):
                        base = pc.cast(base, val.type)
                    elif pa.types.is_null(val.type):
                        val = pc.cast(val, base.type)
                    else:
                        val = pc.cast(val, base.type, safe=False)
            else:
                base = pa.nulls(n, val.type)
            rb = _set_column(rb, col, pc.if_else(mask, val, base))
        elif kind == "expand":
            _, e = step
            rb = _expand_event(rb, as_array(ev.eval(e), n))
        elif kind == "del":
            _, col = step
            if col in rb.schema.names:
                rb = rb.drop_columns([col])
        elif kind == "filter":
            _, slot = step
            if slot is None:  # top-level abort: drop every row
                keep = pa.array([False] * n, pa.bool_())
            else:
                keep = pc.invert(masks[slot])
            rb = rb.filter(keep)
            # live masks must track the surviving rows or later branch
            # steps would index a stale row set
            masks = {k: m.filter(keep) for k, m in masks.items()}
    hidden = [c for c in rb.schema.names if c.startswith(_LOCAL_PREFIX)]
    if hidden:
        rb = rb.drop_columns(hidden)
    return MessageBatch(rb)


def _expand_event(rb: pa.RecordBatch, vals: pa.Array) -> pa.RecordBatch:
    """`. = parse_json!(col)`: decode each row's JSON object into typed
    columns (same vectorized tier as json_to_arrow) and replace the event's
    data columns with them. `__meta_*` and hidden local columns survive —
    VRL holds metadata outside the event the same way."""
    from arkflow_tpu.errors import ArkError
    from arkflow_tpu.plugins.codec.json_codec import JsonCodec

    payloads = []
    for v in vals:
        pv = v.as_py()
        if pv is None:
            payloads.append(b"{}")
        elif isinstance(pv, bytes):
            payloads.append(pv)
        else:
            payloads.append(str(pv).encode())
    codec = JsonCodec()
    try:
        decoded = codec.decode_many(payloads)
    except (ArkError, pa.ArrowInvalid):
        # parse_json! is fallible PER EVENT in reference VRL: one malformed
        # row must not fail the whole batch (under at-least-once replay that
        # would wedge the stream on a single poison record). Fall back to
        # row-wise validation, substituting {} for any row that cannot
        # become exactly one event — malformed JSON and multi-object NDJSON
        # payloads alike (the strict path rejects the latter batch-wide).
        fixed = []
        for p in payloads:
            try:
                ok = codec.decode(p).num_rows == 1
            except (ArkError, pa.ArrowInvalid):
                ok = False
            fixed.append(p if ok else b"{}")
        try:
            decoded = codec.decode_many(fixed)
        except (ArkError, pa.ArrowInvalid) as e:
            raise ArkError(f"vrl: '. = parse_json!' failed to decode: {e}") from e
    if decoded.num_rows != rb.num_rows:
        raise ArkError(
            "vrl: '. = parse_json!' payloads must be one object per row "
            f"(got {decoded.num_rows} rows from {rb.num_rows})")
    keep = [c for c in rb.schema.names
            if c.startswith("__meta_") or c.startswith(_LOCAL_PREFIX)]
    arrays = [rb.column(rb.schema.names.index(c)) for c in keep]
    names = list(keep)
    drb = decoded.record_batch
    for c in drb.schema.names:
        if c not in names:
            names.append(c)
            arrays.append(drb.column(drb.schema.names.index(c)))
    return pa.RecordBatch.from_arrays(arrays, names=names)


def _bool(v, n: int) -> pa.Array:
    a = as_array(v, n)
    if not pa.types.is_boolean(a.type):
        a = pc.cast(a, pa.bool_())
    return a


def _set_column(rb: pa.RecordBatch, col: str, arr: pa.Array) -> pa.RecordBatch:
    names = list(rb.schema.names)
    arrays = list(rb.columns)
    if col in names:
        arrays[names.index(col)] = arr
    else:
        names.append(col)
        arrays.append(arr)
    return pa.RecordBatch.from_arrays(arrays, names=names)
