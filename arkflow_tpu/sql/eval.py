"""Vectorized expression evaluation over Arrow batches.

Compiles the parsed AST onto ``pyarrow.compute`` kernels — columnar, no
per-row Python in the hot path. This is the engine behind WHERE clauses,
projections, and ``Expr``-typed config values (the reference evaluates such
expressions through DataFusion physical exprs with a global cache,
ref: crates/arkflow-plugin/src/expr/mod.rs:27-118).

Evaluation returns either a ``pa.Array`` of the batch's length or a Python
scalar (literals/constant folds); callers broadcast with ``as_array`` when
they need a column.
"""

from __future__ import annotations

import functools
from typing import Any

import pyarrow as pa
import pyarrow.compute as pc

from arkflow_tpu.batch import MessageBatch
from arkflow_tpu.errors import UnsupportedSql
from arkflow_tpu.sql import ast
from arkflow_tpu.sql.functions import as_array, call_scalar
from arkflow_tpu.sql.parser import parse_expression

_SQL_TYPES: dict[str, pa.DataType] = {
    "int": pa.int64(),
    "integer": pa.int64(),
    "bigint": pa.int64(),
    "smallint": pa.int32(),
    "tinyint": pa.int8(),
    "float": pa.float64(),
    "double": pa.float64(),
    "double precision": pa.float64(),
    "real": pa.float32(),
    "decimal": pa.float64(),
    "numeric": pa.float64(),
    "text": pa.string(),
    "varchar": pa.string(),
    "char": pa.string(),
    "string": pa.string(),
    "boolean": pa.bool_(),
    "bool": pa.bool_(),
    "binary": pa.binary(),
    "blob": pa.binary(),
    "bytea": pa.binary(),
    "timestamp": pa.timestamp("us"),
    "date": pa.date32(),
}


def sql_type_to_arrow(name: str) -> pa.DataType:
    t = _SQL_TYPES.get(name.lower())
    if t is None:
        raise UnsupportedSql(f"unknown SQL type {name!r}")
    return t


_CMP = {
    "=": pc.equal,
    "!=": pc.not_equal,
    "<": pc.less,
    "<=": pc.less_equal,
    ">": pc.greater,
    ">=": pc.greater_equal,
}

_ARITH = {
    "+": pc.add,
    "-": pc.subtract,
    "*": pc.multiply,
    "/": pc.divide,
}


def _is_arr(v: Any) -> bool:
    return isinstance(v, (pa.Array, pa.ChunkedArray))


def _to_bool(v: Any, n: int) -> pa.Array:
    a = as_array(v, n)
    if not pa.types.is_boolean(a.type):
        a = pc.cast(a, pa.bool_())
    return a


class Evaluator:
    """Evaluates AST expressions against one record batch.

    ``columns`` maps bare and table-qualified names to arrays, so the same
    evaluator serves single-table queries and join ON conditions.
    """

    def __init__(self, columns: dict[str, pa.Array], num_rows: int):
        self.columns = columns
        self.n = num_rows

    @classmethod
    def for_batch(cls, batch: MessageBatch | pa.RecordBatch, table: str | None = None) -> "Evaluator":
        rb = batch.record_batch if isinstance(batch, MessageBatch) else batch
        cols: dict[str, pa.Array] = {}
        for i, f in enumerate(rb.schema):
            cols[f.name] = rb.column(i)
            if table:
                cols[f"{table}.{f.name}"] = rb.column(i)
        return cls(cols, rb.num_rows)

    def eval(self, e: ast.Expr) -> Any:
        m = getattr(self, f"_eval_{type(e).__name__.lower()}", None)
        if m is None:
            raise UnsupportedSql(f"cannot evaluate {type(e).__name__}")
        return m(e)

    # -- node handlers -----------------------------------------------------

    def _eval_literal(self, e: ast.Literal) -> Any:
        return e.value

    def _eval_column(self, e: ast.Column) -> pa.Array:
        key = f"{e.table}.{e.name}" if e.table else e.name
        arr = self.columns.get(key)
        if arr is None and e.table is None:
            # case-insensitive fallback
            for k, v in self.columns.items():
                if k.lower() == e.name.lower():
                    return v
        if arr is None:
            raise UnsupportedSql(f"no such column {key!r} (have: {sorted(self.columns)})")
        return arr

    def _eval_unary(self, e: ast.Unary) -> Any:
        v = self.eval(e.operand)
        if e.op == "not":
            return pc.invert(_to_bool(v, self.n))
        if e.op == "-":
            return pc.negate(v) if _is_arr(v) else (None if v is None else -v)
        return v

    def _eval_binary(self, e: ast.Binary) -> Any:
        op = e.op
        if op == "and":
            return pc.and_kleene(_to_bool(self.eval(e.left), self.n), _to_bool(self.eval(e.right), self.n))
        if op == "or":
            return pc.or_kleene(_to_bool(self.eval(e.left), self.n), _to_bool(self.eval(e.right), self.n))
        l, r = self.eval(e.left), self.eval(e.right)
        if op in _CMP:
            if not _is_arr(l) and not _is_arr(r):
                return _CMP[op](pa.scalar(l), pa.scalar(r)).as_py()
            l2, r2 = self._align(l, r)
            return _CMP[op](l2, r2)
        if op in _ARITH:
            if not _is_arr(l) and not _is_arr(r):
                if l is None or r is None:
                    return None
                if op == "+":
                    return l + r
                if op == "-":
                    return l - r
                if op == "*":
                    return l * r
                return None if r == 0 else l / r  # x/0 -> NULL (sqlite semantics)
            l2, r2 = self._align(l, r)
            return _ARITH[op](l2, r2)
        if op == "%":
            return call_scalar("mod", [l, r], self.n)
        if op == "||":
            return call_scalar("concat", [l, r], self.n)
        if op in ("like", "ilike"):
            if _is_arr(r):
                raise UnsupportedSql("LIKE pattern must be a literal")
            return pc.match_like(as_array(l, self.n), str(r), ignore_case=(op == "ilike"))
        raise UnsupportedSql(f"unknown operator {op!r}")

    def _align(self, l: Any, r: Any) -> tuple[Any, Any]:
        """Broadcast scalars against arrays; let arrow handle numeric promotion."""
        if _is_arr(l) and not _is_arr(r):
            return l, pa.scalar(r) if r is not None else pa.scalar(None, type=l.type)
        if _is_arr(r) and not _is_arr(l):
            return pa.scalar(l) if l is not None else pa.scalar(None, type=r.type), r
        return l, r

    def _eval_isnull(self, e: ast.IsNull) -> Any:
        v = self.eval(e.operand)
        if not _is_arr(v):
            res = v is None
            return (not res) if e.negated else res
        return pc.is_valid(v) if e.negated else pc.is_null(v)

    def _eval_inlist(self, e: ast.InList) -> Any:
        v = as_array(self.eval(e.operand), self.n)
        items = [self.eval(i) for i in e.items]
        if any(_is_arr(i) for i in items):
            raise UnsupportedSql("IN list items must be literals")
        value_set = pa.array(items, type=v.type if items and all(i is None for i in items) else None)
        res = pc.is_in(v, value_set=value_set)
        return pc.invert(res) if e.negated else res

    def _eval_between(self, e: ast.Between) -> Any:
        v = self.eval(e.operand)
        low, high = self.eval(e.low), self.eval(e.high)
        l2a, l2b = self._align(v, low)
        h2a, h2b = self._align(v, high)
        res = pc.and_kleene(pc.greater_equal(l2a, l2b), pc.less_equal(h2a, h2b))
        return pc.invert(res) if e.negated else res

    def _eval_func(self, e: ast.Func) -> Any:
        if e.is_star:
            raise UnsupportedSql(f"{e.name}(*) is an aggregate; not valid in scalar context")
        args = [self.eval(a) for a in e.args]
        return call_scalar(e.name, args, self.n)

    def _eval_cast(self, e: ast.Cast) -> Any:
        v = self.eval(e.operand)
        t = sql_type_to_arrow(e.type_name)
        if _is_arr(v):
            return pc.cast(v, t, safe=False)
        if v is None:
            return None
        return pc.cast(pa.scalar(v), t, safe=False).as_py()

    def _eval_case(self, e: ast.Case) -> Any:
        # Build from the end: ELSE, then fold WHENs backwards with if_else.
        opv = self.eval(e.operand) if e.operand is not None else None
        result = as_array(self.eval(e.otherwise), self.n) if e.otherwise is not None else None
        for cond_e, val_e in reversed(e.whens):
            if e.operand is not None:
                la, ra = self._align(opv, self.eval(cond_e))
                cond = pc.equal(la, ra)
            else:
                cond = _to_bool(self.eval(cond_e), self.n)
            cond = as_array(cond, self.n)
            val = as_array(self.eval(val_e), self.n)
            if result is None:
                result = pa.nulls(self.n, val.type)
            if result.type != val.type and pa.types.is_null(result.type):
                result = pc.cast(result, val.type)
            result = pc.if_else(cond, val, result)
        return result if result is not None else None

    def _eval_star(self, e: ast.Star) -> Any:
        raise UnsupportedSql("* is only valid as a select item")


@functools.lru_cache(maxsize=1024)
def _parse_cached(expr: str) -> ast.Expr:
    return parse_expression(expr)


def evaluate_expression(batch: MessageBatch | pa.RecordBatch, expr: str) -> pa.Array:
    """Evaluate a SQL expression string against a batch, returning a column.

    Parsed ASTs are cached globally, mirroring the reference's physical-expr
    cache (ref expr/mod.rs:92).
    """
    ev = Evaluator.for_batch(batch)
    out = ev.eval(_parse_cached(expr))
    return as_array(out, ev.n)
