"""Recursive-descent SQL parser.

Parses the SELECT dialect the engine executes. Anything outside the grammar
raises ``UnsupportedSql`` — the engine then routes the raw query string to the
sqlite fallback (which accepts a much larger dialect). DDL/DML is rejected
outright, mirroring the reference's ``SQLOptions`` guard
(ref: crates/arkflow-plugin/src/processor/sql.rs:192-195).
"""

from __future__ import annotations

from typing import Optional

from arkflow_tpu.errors import UnsupportedSql
from arkflow_tpu.sql import ast
from arkflow_tpu.sql.lexer import Token, tokenize

_FORBIDDEN_HEADS = {
    "insert", "update", "delete", "create", "drop", "alter", "truncate",
    "attach", "pragma", "vacuum", "replace", "grant", "revoke", "copy", "set",
}


def assert_query_only(sql: str) -> None:
    """Reject anything but SELECT / WITH...SELECT, like the reference's SQLOptions.

    Works on the token stream (comments/strings already stripped), so a leading
    ``/**/`` or ``--`` comment cannot smuggle DDL/DML past the guard. The
    sqlite fallback additionally installs a read-only authorizer as defence in
    depth.
    """
    toks = tokenize(sql)
    if not toks or toks[0].kind == "eof":
        raise UnsupportedSql("empty statement")
    head = toks[0]
    head_word = head.value.lower()
    if head.is_kw("select"):
        return
    if head.is_kw("with"):
        # CTE prefix: the statement verb is the first top-level keyword after
        # the WITH list; require it to be SELECT (forbids WITH ... DELETE).
        depth = 0
        for t in toks[1:]:
            if t.kind == "op" and t.value == "(":
                depth += 1
            elif t.kind == "op" and t.value == ")":
                depth -= 1
            elif depth == 0 and (t.kind in ("kw", "ident")) and t.value.lower() in (
                _FORBIDDEN_HEADS | {"select"}
            ):
                if t.value.lower() == "select":
                    return
                raise UnsupportedSql(
                    f"statement type {t.value!r} is not allowed; queries only"
                )
        raise UnsupportedSql("WITH clause without a SELECT body")
    raise UnsupportedSql(f"statement type {head_word!r} is not allowed; queries only")


class Parser:
    def __init__(self, sql: str):
        self.toks = tokenize(sql)
        self.i = 0

    # -- plumbing ----------------------------------------------------------

    def peek(self) -> Token:
        return self.toks[self.i]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_kw(self, *names: str) -> Optional[Token]:
        if self.peek().is_kw(*names):
            return self.next()
        return None

    def accept_op(self, *ops: str) -> Optional[Token]:
        t = self.peek()
        if t.kind == "op" and t.value in ops:
            return self.next()
        return None

    def expect_kw(self, name: str) -> Token:
        t = self.next()
        if not (t.kind == "kw" and t.value == name):
            raise UnsupportedSql(f"expected {name.upper()} at pos {t.pos}, got {t.value!r}")
        return t

    def expect_op(self, op: str) -> Token:
        t = self.next()
        if not (t.kind == "op" and t.value == op):
            raise UnsupportedSql(f"expected {op!r} at pos {t.pos}, got {t.value!r}")
        return t

    # -- entry points ------------------------------------------------------

    def parse_select(self) -> ast.Select:
        sel = self._select()
        t = self.peek()
        if t.kind == "op" and t.value == ";":
            self.next()
            t = self.peek()
        if t.kind != "eof":
            raise UnsupportedSql(f"trailing tokens at pos {t.pos}: {t.value!r}")
        return sel

    def parse_expression(self) -> ast.Expr:
        e = self._expr()
        t = self.peek()
        if t.kind != "eof":
            raise UnsupportedSql(f"trailing tokens at pos {t.pos}: {t.value!r}")
        return e

    # -- select ------------------------------------------------------------

    def _select(self) -> ast.Select:
        self.expect_kw("select")
        sel = ast.Select()
        if self.accept_kw("distinct"):
            sel.distinct = True
        elif self.accept_kw("all"):
            pass
        sel.items = [self._select_item()]
        while self.accept_op(","):
            sel.items.append(self._select_item())
        if self.accept_kw("from"):
            sel.table = self._table_ref()
            while True:
                join = self._maybe_join()
                if join is None:
                    break
                sel.joins.append(join)
        if self.accept_kw("where"):
            sel.where = self._expr()
        if self.accept_kw("group"):
            self.expect_kw("by")
            sel.group_by = [self._expr()]
            while self.accept_op(","):
                sel.group_by.append(self._expr())
        if self.accept_kw("having"):
            sel.having = self._expr()
        if self.accept_kw("union"):
            raise UnsupportedSql("UNION not supported natively")
        if self.accept_kw("order"):
            self.expect_kw("by")
            sel.order_by = [self._order_item()]
            while self.accept_op(","):
                sel.order_by.append(self._order_item())
        if self.accept_kw("limit"):
            sel.limit = self._int_literal()
        if self.accept_kw("offset"):
            sel.offset = self._int_literal()
        return sel

    def _int_literal(self) -> int:
        t = self.next()
        if t.kind != "number" or not t.value.isdigit():
            raise UnsupportedSql(f"expected integer at pos {t.pos}")
        return int(t.value)

    def _select_item(self) -> ast.SelectItem:
        t = self.peek()
        if t.kind == "op" and t.value == "*":
            self.next()
            return ast.SelectItem(ast.Star())
        e = self._expr()
        alias = None
        if self.accept_kw("as"):
            at = self.next()
            if at.kind not in ("ident", "string"):
                raise UnsupportedSql(f"expected alias at pos {at.pos}")
            alias = at.value
        elif self.peek().kind == "ident":
            alias = self.next().value
        return ast.SelectItem(e, alias)

    def _table_ref(self) -> ast.TableRef:
        t = self.next()
        if t.kind == "op" and t.value == "(":
            raise UnsupportedSql("subquery in FROM not supported natively")
        if t.kind != "ident":
            raise UnsupportedSql(f"expected table name at pos {t.pos}")
        alias = None
        if self.accept_kw("as"):
            alias = self.next().value
        elif self.peek().kind == "ident":
            alias = self.next().value
        return ast.TableRef(t.value, alias)

    def _maybe_join(self) -> Optional[ast.Join]:
        kind = None
        if self.accept_kw("cross"):
            kind = "cross"
        elif self.accept_kw("inner"):
            kind = "inner"
        elif self.accept_kw("left"):
            self.accept_kw("outer")
            kind = "left"
        elif self.accept_kw("right"):
            self.accept_kw("outer")
            kind = "right"
        elif self.accept_kw("full"):
            self.accept_kw("outer")
            kind = "full"
        elif self.peek().is_kw("join"):
            kind = "inner"
        if kind is None:
            return None
        self.expect_kw("join")
        table = self._table_ref()
        on = None
        if kind != "cross":
            self.expect_kw("on")
            on = self._expr()
        return ast.Join(kind, table, on)

    def _window_spec(self, f: ast.Func) -> ast.WindowFunc:
        """OVER (PARTITION BY ... ORDER BY ...) — explicit frames are not
        representable natively and reroute to the fallback engine."""
        self.expect_op("(")
        partition: list[ast.Expr] = []
        if self.accept_kw("partition"):
            self.expect_kw("by")
            partition = [self._expr()]
            while self.accept_op(","):
                partition.append(self._expr())
        order: list[ast.OrderItem] = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            order = [self._order_item()]
            while self.accept_op(","):
                order.append(self._order_item())
        t = self.peek()
        if t.kind == "ident" and t.value.lower() in ("rows", "range", "groups"):
            raise UnsupportedSql("explicit window frames not supported natively")
        self.expect_op(")")
        return ast.WindowFunc(f, tuple(partition), tuple(order))

    def _order_item(self) -> ast.OrderItem:
        e = self._expr()
        asc = True
        if self.accept_kw("asc"):
            asc = True
        elif self.accept_kw("desc"):
            asc = False
        if self.accept_kw("nulls"):
            if not (self.accept_kw("first") or self.accept_kw("last")):
                raise UnsupportedSql("expected FIRST/LAST after NULLS")
        return ast.OrderItem(e, asc)

    # -- expressions (precedence climbing) ---------------------------------

    def _expr(self) -> ast.Expr:
        return self._or()

    def _or(self) -> ast.Expr:
        left = self._and()
        while self.accept_kw("or"):
            left = ast.Binary("or", left, self._and())
        return left

    def _and(self) -> ast.Expr:
        left = self._not()
        while self.accept_kw("and"):
            left = ast.Binary("and", left, self._not())
        return left

    def _not(self) -> ast.Expr:
        if self.accept_kw("not"):
            return ast.Unary("not", self._not())
        return self._comparison()

    def _comparison(self) -> ast.Expr:
        left = self._additive()
        t = self.peek()
        if t.kind == "op" and t.value in ("=", "!=", "<>", "<", "<=", ">", ">="):
            self.next()
            op = "!=" if t.value == "<>" else t.value
            return ast.Binary(op, left, self._additive())
        if t.is_kw("is"):
            self.next()
            negated = bool(self.accept_kw("not"))
            self.expect_kw("null")
            return ast.IsNull(left, negated)
        negated = False
        if t.is_kw("not"):
            # NOT IN / NOT LIKE / NOT BETWEEN
            save = self.i
            self.next()
            if self.peek().is_kw("in", "like", "ilike", "between"):
                negated = True
                t = self.peek()
            else:
                self.i = save
                return left
        if self.peek().is_kw("in"):
            self.next()
            self.expect_op("(")
            if self.peek().is_kw("select"):
                raise UnsupportedSql("IN (subquery) not supported natively")
            items = [self._expr()]
            while self.accept_op(","):
                items.append(self._expr())
            self.expect_op(")")
            return ast.InList(left, tuple(items), negated)
        if self.peek().is_kw("like", "ilike"):
            op = self.next().value
            node = ast.Binary(op, left, self._additive())
            return ast.Unary("not", node) if negated else node
        if self.peek().is_kw("between"):
            self.next()
            low = self._additive()
            self.expect_kw("and")
            high = self._additive()
            return ast.Between(left, low, high, negated)
        return left

    def _additive(self) -> ast.Expr:
        left = self._multiplicative()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("+", "-", "||"):
                self.next()
                left = ast.Binary(t.value, left, self._multiplicative())
            else:
                return left

    def _multiplicative(self) -> ast.Expr:
        left = self._unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("*", "/", "%"):
                self.next()
                left = ast.Binary(t.value, left, self._unary())
            else:
                return left

    def _unary(self) -> ast.Expr:
        t = self.peek()
        if t.kind == "op" and t.value in ("-", "+"):
            self.next()
            operand = self._unary()
            if t.value == "-" and isinstance(operand, ast.Literal) and isinstance(operand.value, (int, float)):
                return ast.Literal(-operand.value)
            return ast.Unary(t.value, operand)
        return self._primary()

    def _primary(self) -> ast.Expr:
        t = self.next()
        if t.kind == "number":
            v = t.value
            if "." in v or "e" in v.lower():
                return ast.Literal(float(v))
            return ast.Literal(int(v))
        if t.kind == "string":
            return ast.Literal(t.value)
        if t.is_kw("true"):
            return ast.Literal(True)
        if t.is_kw("false"):
            return ast.Literal(False)
        if t.is_kw("null"):
            return ast.Literal(None)
        if t.is_kw("cast"):
            self.expect_op("(")
            e = self._expr()
            self.expect_kw("as")
            ty = self.next()
            if ty.kind not in ("ident", "kw"):
                raise UnsupportedSql(f"expected type name at pos {ty.pos}")
            type_name = ty.value.lower()
            # e.g. DOUBLE PRECISION / VARCHAR(10)
            if self.peek().kind == "ident":
                type_name += " " + self.next().value.lower()
            if self.accept_op("("):
                self._int_literal()
                if self.accept_op(","):
                    self._int_literal()
                self.expect_op(")")
            self.expect_op(")")
            return ast.Cast(e, type_name)
        if t.is_kw("case"):
            operand = None
            if not self.peek().is_kw("when"):
                operand = self._expr()
            whens = []
            while self.accept_kw("when"):
                cond = self._expr()
                self.expect_kw("then")
                whens.append((cond, self._expr()))
            otherwise = None
            if self.accept_kw("else"):
                otherwise = self._expr()
            self.expect_kw("end")
            return ast.Case(operand, tuple(whens), otherwise)
        if t.kind == "op" and t.value == "(":
            if self.peek().is_kw("select"):
                raise UnsupportedSql("scalar subquery not supported natively")
            e = self._expr()
            self.expect_op(")")
            return e
        if t.kind == "ident" or (t.kind == "kw" and t.value in ("left", "right")):
            name = t.value
            # function call?
            if self.peek().kind == "op" and self.peek().value == "(":
                self.next()
                distinct = bool(self.accept_kw("distinct"))
                if self.peek().kind == "op" and self.peek().value == "*":
                    self.next()
                    self.expect_op(")")
                    f = ast.Func(name.lower(), (), distinct, is_star=True)
                elif self.peek().kind == "op" and self.peek().value == ")":
                    self.next()
                    f = ast.Func(name.lower(), (), distinct)
                else:
                    args = [self._expr()]
                    while self.accept_op(","):
                        args.append(self._expr())
                    self.expect_op(")")
                    f = ast.Func(name.lower(), tuple(args), distinct)
                if self.peek().is_kw("over"):
                    self.next()
                    return self._window_spec(f)
                return f
            # qualified column?
            if self.peek().kind == "op" and self.peek().value == ".":
                self.next()
                nxt = self.next()
                if nxt.kind == "op" and nxt.value == "*":
                    return ast.Star(table=name)
                if nxt.kind != "ident":
                    raise UnsupportedSql(f"expected column after '.' at pos {nxt.pos}")
                return ast.Column(nxt.value, table=name)
            return ast.Column(name)
        raise UnsupportedSql(f"unexpected token {t.value!r} at pos {t.pos}")


def parse_select(sql: str) -> ast.Select:
    assert_query_only(sql)
    return Parser(sql).parse_select()


def parse_expression(expr: str) -> ast.Expr:
    return Parser(expr).parse_expression()
