"""Arrow-native SQL engine (the DataFusion-equivalent).

The reference embeds DataFusion and registers the in-flight batch as table
``flow`` (ref: crates/arkflow-plugin/src/processor/sql.rs:38,112-120). Neither
DataFusion nor DuckDB is available in this image, so this package implements
the same contract in two tiers:

- **Native tier** (``planner.py``): SELECT / WHERE / GROUP BY / ORDER BY /
  LIMIT compiled straight onto ``pyarrow.compute`` vectorized kernels —
  zero-copy, columnar, no row materialisation. This covers the streaming hot
  path (filters, projections, aggregations).
- **Fallback tier** (``fallback.py``): anything the native planner doesn't
  support (joins, subqueries, CTEs, window functions) is executed by the
  stdlib ``sqlite3`` engine with batches bridged in as tables. Correct, not
  fast — the native tier owns the hot path.

``SessionContext`` (``engine.py``) is the user-facing object; a
``ContextPool`` mirrors the reference's fixed 4-context pool
(ref context_pool.rs:30-131). Scalar/aggregate UDFs registered via
``arkflow_tpu.sql.functions`` are visible in both tiers
(ref udf/mod.rs:38-43).
"""

from arkflow_tpu.sql.engine import ContextPool, SessionContext  # noqa: F401
from arkflow_tpu.sql.eval import evaluate_expression  # noqa: F401
from arkflow_tpu.sql.functions import register_aggregate_udf, register_scalar_udf  # noqa: F401
