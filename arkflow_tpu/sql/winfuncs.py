"""Vectorized window-function execution over Arrow batches.

Computes ``func(...) OVER (PARTITION BY ... ORDER BY ...)`` columns without
row-at-a-time Python: one stable multi-key sort (``pc.sort_indices``), then
numpy segment arithmetic over partition/peer boundaries, then a scatter back
to input order. This is the native tier the reference gets from DataFusion's
window executors (ref: crates/arkflow-plugin/src/processor/sql.rs:112-129 —
DataFusion plans window exprs natively); anything outside the supported
surface raises ``UnsupportedSql`` and reroutes to the sqlite fallback.

Supported: row_number, rank, dense_rank, ntile, lag, lead, first_value,
last_value, nth_value, and sum/count/avg/min/max with default frames
(whole partition when unordered; RANGE UNBOUNDED PRECEDING..CURRENT ROW —
i.e. running-with-peers — when ordered, including running min/max via a
Hillis-Steele scan). NaN follows Postgres/DataFusion ordering: a value, not
NULL — frames containing one yield NaN for sum/avg/max, min skips it.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from arkflow_tpu.errors import UnsupportedSql
from arkflow_tpu.sql import ast
from arkflow_tpu.sql.functions import as_array

_RANKING = {"row_number", "rank", "dense_rank", "ntile", "lag", "lead",
            "first_value", "last_value", "nth_value"}  # frame-free executors
_AGGS = {"sum", "count", "avg", "mean", "min", "max"}


def is_window_supported(name: str) -> bool:
    return name in _RANKING or name in _AGGS


def _int_literal_arg(f: ast.Func, i: int, default: int) -> int:
    if len(f.args) <= i:
        return default
    a = f.args[i]
    if not (isinstance(a, ast.Literal) and isinstance(a.value, int)):
        raise UnsupportedSql(f"{f.name} argument {i + 1} must be an integer literal")
    return a.value


def _changes(sorted_arr: pa.Array, n: int) -> np.ndarray:
    """Bool[n-1]: sorted row i+1 differs from row i (nulls compare equal)."""
    a, b = sorted_arr.slice(1), sorted_arr.slice(0, n - 1)
    ne = pc.fill_null(pc.not_equal(a, b), False)
    nv = pc.xor(pc.is_null(a), pc.is_null(b))
    return pc.or_(ne, nv).to_numpy(zero_copy_only=False).astype(bool)


def compute_window(win: ast.WindowFunc, ev, n: int) -> pa.Array:
    """Evaluate one window expression against ``ev``'s batch of ``n`` rows."""
    f = win.func
    name = "avg" if f.name == "mean" else f.name
    if not is_window_supported(name):
        raise UnsupportedSql(f"window function {f.name!r} not supported natively")
    if f.distinct:
        raise UnsupportedSql("DISTINCT inside a window function not supported natively")
    if n == 0:
        int_typed = name in ("row_number", "rank", "dense_rank", "ntile", "count")
        return pa.nulls(0, pa.int64() if int_typed else pa.float64())

    # one stable sort over (partition keys, order keys)
    cols: dict[str, pa.Array] = {}
    sort_keys: list[tuple[str, str]] = []
    for i, p in enumerate(win.partition_by):
        cols[f"__p{i}"] = as_array(ev.eval(p), n)
        sort_keys.append((f"__p{i}", "ascending"))
    for i, oi in enumerate(win.order_by):
        cols[f"__o{i}"] = as_array(ev.eval(oi.expr), n)
        sort_keys.append((f"__o{i}", "ascending" if oi.asc else "descending"))
    if sort_keys:
        idx = pc.sort_indices(pa.table(cols), sort_keys=sort_keys)
        idx_np = idx.to_numpy()
    else:
        idx = pa.array(np.arange(n), pa.int64())
        idx_np = np.arange(n)

    # partition / peer boundaries in sorted space
    part_change = np.zeros(n - 1, bool)
    for i in range(len(win.partition_by)):
        part_change |= _changes(cols[f"__p{i}"].take(idx), n)
    peer_change = part_change.copy()
    for i in range(len(win.order_by)):
        peer_change |= _changes(cols[f"__o{i}"].take(idx), n)
    new_part = np.r_[True, part_change]
    # without ORDER BY every partition row is a peer of every other, which
    # also makes the running-aggregate formulas degenerate to whole-partition
    new_peer = np.r_[True, peer_change] if win.order_by else new_part

    pos = np.arange(n)
    part_id = np.cumsum(new_part) - 1
    starts = np.flatnonzero(new_part)
    ends_excl = np.r_[starts[1:], n]
    part_start = starts[part_id]          # per sorted row
    part_end = ends_excl[part_id] - 1
    peer_id = np.cumsum(new_peer) - 1
    peer_starts = np.flatnonzero(new_peer)
    peer_end = np.r_[peer_starts[1:], n][peer_id] - 1

    if name in _RANKING:
        out = _ranking(name, f, ev, n, idx, idx_np, pos,
                       part_start, part_end, peer_id, peer_starts, peer_end,
                       ends_excl, part_id)
    else:
        out = _aggregate(name, f, ev, n, idx, idx_np, part_start, peer_end)
    return out


def _scatter(values, idx_np: np.ndarray, n: int):
    """Reorder a sorted-space result back to input order."""
    inv = np.empty(n, np.int64)
    inv[idx_np] = np.arange(n)
    if isinstance(values, (pa.Array, pa.ChunkedArray)):
        return values.take(pa.array(inv))
    out = np.empty(n, values.dtype)
    out[idx_np] = values
    return pa.array(out)


def _ranking(name, f, ev, n, idx, idx_np, pos, part_start, part_end,
             peer_id, peer_starts, peer_end, ends_excl, part_id) -> pa.Array:
    if name == "row_number":
        return _scatter(pos - part_start + 1, idx_np, n)
    if name == "rank":
        return _scatter(peer_starts[peer_id] - part_start + 1, idx_np, n)
    if name == "dense_rank":
        return _scatter(peer_id - peer_id[part_start] + 1, idx_np, n)
    if name == "ntile":
        k = _int_literal_arg(f, 0, 0)
        if k <= 0:
            raise UnsupportedSql("ntile requires a positive integer argument")
        size = ends_excl[part_id] - part_start
        pos0 = pos - part_start
        q, r = size // k, size % k
        thresh = (q + 1) * r
        bucket = np.where(pos0 < thresh,
                          pos0 // np.maximum(q + 1, 1),
                          r + (pos0 - thresh) // np.maximum(q, 1))
        return _scatter(bucket + 1, idx_np, n)

    # value-bearing functions
    if not f.args:
        raise UnsupportedSql(f"{name} requires a value argument")
    vals = as_array(ev.eval(f.args[0]), n).take(idx)  # sorted space
    if name in ("lag", "lead"):
        k = _int_literal_arg(f, 1, 1)
        src = pos - k if name == "lag" else pos + k
        valid = (src >= part_start) & (src <= part_end)
        taken = vals.take(pa.array(np.clip(src, 0, n - 1)))
        if len(f.args) >= 3:
            d = f.args[2]
            if not isinstance(d, ast.Literal):
                raise UnsupportedSql(f"{name} default must be a literal")
            fallback = as_array(d.value, n)
            if fallback.type != taken.type and not pa.types.is_null(fallback.type):
                fallback = pc.cast(fallback, taken.type, safe=False)
        else:
            fallback = pa.nulls(n, taken.type)
        res = pc.if_else(pa.array(valid), taken, fallback)
        return _scatter(res, idx_np, n)
    if name == "first_value":
        return _scatter(vals.take(pa.array(part_start)), idx_np, n)
    if name == "last_value":
        # default frame ends at the current row's last peer
        return _scatter(vals.take(pa.array(peer_end)), idx_np, n)
    if name == "nth_value":
        k = _int_literal_arg(f, 1, 0)
        if k <= 0:
            raise UnsupportedSql("nth_value requires a positive integer argument")
        src = part_start + (k - 1)
        valid = src <= peer_end  # frame = start..current peer group
        taken = vals.take(pa.array(np.clip(src, 0, n - 1)))
        res = pc.if_else(pa.array(valid), taken, pa.nulls(n, taken.type))
        return _scatter(res, idx_np, n)
    raise UnsupportedSql(f"window function {name!r} not supported natively")


def _aggregate(name, f, ev, n, idx, idx_np, part_start, peer_end) -> pa.Array:
    """sum/count/avg/min/max over start..peer_end (= whole partition when
    unordered, running-with-peers when ordered) via prefix sums."""
    has_nonfinite = False
    nan_np = pinf_np = ninf_np = None
    if f.is_star:
        if name != "count":
            raise UnsupportedSql(f"{name}(*) is not a window aggregate")
        valid_np = np.ones(n, np.int64)
        x = None
        integral = False
    else:
        if len(f.args) != 1:
            raise UnsupportedSql(f"window aggregate {name} takes one argument")
        vals = as_array(ev.eval(f.args[0]), n).take(idx)
        if not (pa.types.is_integer(vals.type) or pa.types.is_floating(vals.type)
                or pa.types.is_boolean(vals.type) or pa.types.is_decimal(vals.type)):
            raise UnsupportedSql(f"window {name} over non-numeric values")
        valid_np = pc.is_valid(vals).to_numpy(zero_copy_only=False).astype(np.int64)
        valid_b = valid_np.astype(bool)
        integral = pa.types.is_integer(vals.type) or pa.types.is_boolean(vals.type)
        if integral:
            # exact int64 accumulation: float64 prefix sums would silently
            # round sums past 2^53
            x = pc.fill_null(pc.cast(vals, pa.int64(), safe=False), 0).to_numpy(
                zero_copy_only=False).astype(np.int64)
        else:
            x = pc.cast(vals, pa.float64(), safe=False).to_numpy(zero_copy_only=False)
            x = np.where(valid_b, x, 0.0)
            # NaN is a VALUE, not NULL (Postgres/DataFusion ordering: NaN
            # sorts above every number). Prefix sums would smear it into
            # every later frame, so zero it here and re-mark exactly the
            # frames whose window contains one via a NaN-count prefix.
            # +/-inf smear the same way (inf - inf = NaN in later frames),
            # so they get the same treatment with sign-correct overlays.
            if not np.isfinite(x).all():  # rare: keep the hot path lean
                has_nonfinite = True
                nan_np = np.isnan(x).astype(np.int64)
                pinf_np = (x == np.inf).astype(np.int64)
                ninf_np = (x == -np.inf).astype(np.int64)
                x = np.where((nan_np | pinf_np | ninf_np).astype(bool), 0.0, x)

    ccum = np.r_[0, np.cumsum(valid_np)]
    cnt = ccum[peer_end + 1] - ccum[part_start]
    if name == "count":
        return _scatter(cnt, idx_np, n)

    frame_nans = None
    if has_nonfinite:
        ncum = np.r_[0, np.cumsum(nan_np)]
        frame_nans = ncum[peer_end + 1] - ncum[part_start]

    if name in ("min", "max"):
        if integral:
            fill = np.iinfo(np.int64).max if name == "min" else np.iinfo(np.int64).min
            xm = np.where(valid_b, x, fill)
        elif has_nonfinite:
            fill = np.inf if name == "min" else -np.inf
            # restore genuine infinities (zeroed above for the sum path);
            # min skips NaN (it sorts above everything); max over a frame
            # holding one IS NaN — handled below via frame_nans
            xv = np.where(pinf_np.astype(bool), np.inf,
                          np.where(ninf_np.astype(bool), -np.inf, x))
            xm = np.where(valid_b & ~nan_np.astype(bool), xv, fill)
        else:
            fill = np.inf if name == "min" else -np.inf
            xm = np.where(valid_b, x, fill)
        acc = _running_extreme(xm, part_start, n, is_min=(name == "min"))
        per_row = acc[peer_end]
        if not integral and has_nonfinite:
            if name == "max":
                per_row = np.where(frame_nans > 0, np.nan, per_row)
            else:
                # all values in frame NaN -> min is NaN
                per_row = np.where((cnt > 0) & (frame_nans == cnt), np.nan, per_row)
        res = pa.array(per_row)
        null_t = pa.int64() if integral else pa.float64()
        res = pc.if_else(pa.array(cnt > 0), res, pa.nulls(n, null_t))
        return _scatter(res, idx_np, n)

    scum = np.r_[0 if integral else 0.0, np.cumsum(x)]
    s = scum[peer_end + 1] - scum[part_start]
    if not integral and has_nonfinite:
        # overlay non-finite frames with IEEE semantics: +inf-only -> +inf,
        # -inf-only -> -inf, both (or any NaN) -> NaN
        pcum = np.r_[0, np.cumsum(pinf_np)]
        ncum2 = np.r_[0, np.cumsum(ninf_np)]
        fp = pcum[peer_end + 1] - pcum[part_start]
        fn = ncum2[peer_end + 1] - ncum2[part_start]
        s = np.where((fp > 0) & (fn == 0), np.inf, s)
        s = np.where((fn > 0) & (fp == 0), -np.inf, s)
        s = np.where(((fp > 0) & (fn > 0)) | (frame_nans > 0), np.nan, s)
    if name == "avg":
        res = pa.array(np.where(cnt > 0, s / np.maximum(cnt, 1), np.nan))
        return _scatter(pc.if_else(pa.array(cnt > 0), res,
                                   pa.nulls(n, pa.float64())), idx_np, n)
    # sum
    null_t = pa.int64() if integral else pa.float64()
    res = pc.if_else(pa.array(cnt > 0), pa.array(s), pa.nulls(n, null_t))
    return _scatter(res, idx_np, n)


def _running_extreme(xm: np.ndarray, part_start: np.ndarray, n: int,
                     is_min: bool) -> np.ndarray:
    """Per-row min/max over [part_start[i] .. i] in sorted order: a
    Hillis-Steele scan with partition resets. After k rounds acc[i] covers
    the last 2^k rows of its partition ending at i; min/max are idempotent,
    so the overlapping-window merge is exact. log2(longest partition)
    vectorized passes — running MIN/MAX used to bail to the sqlite fallback.
    """
    op = np.minimum if is_min else np.maximum
    acc = xm.copy()
    pos = np.arange(n)
    shift = 1
    while shift < n:
        can = pos >= part_start + shift
        if not can.any():
            break
        shifted = np.empty_like(acc)
        shifted[shift:] = acc[:-shift]
        shifted[:shift] = acc[:shift]  # never read: 'can' is False there
        acc = np.where(can, op(acc, shifted), acc)
        shift <<= 1
    return acc


