"""SQL tokenizer."""

from __future__ import annotations

from dataclasses import dataclass

from arkflow_tpu.errors import UnsupportedSql

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "in", "is", "null", "like", "ilike",
    "between", "case", "when", "then", "else", "end", "cast", "distinct",
    "asc", "desc", "join", "inner", "left", "right", "full", "outer", "cross",
    "on", "union", "all", "true", "false", "exists", "interval", "nulls",
    "first", "last", "with", "over", "partition",
}

_TWO_CHAR = {"<=", ">=", "!=", "<>", "||"}
_ONE_CHAR = set("+-*/%(),.=<>;")


@dataclass
class Token:
    kind: str  # kw | ident | number | string | op | eof
    value: str
    pos: int

    def is_kw(self, *names: str) -> bool:
        return self.kind == "kw" and self.value in names


def tokenize(sql: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i)
            if j < 0:
                raise UnsupportedSql(f"unterminated comment at {i}")
            i = j + 2
            continue
        if c == "'":
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":  # escaped ''
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            if j >= n:
                raise UnsupportedSql(f"unterminated string at {i}")
            toks.append(Token("string", "".join(buf), i))
            i = j + 1
            continue
        if c == '"' or c == "`":
            close = c
            j = sql.find(close, i + 1)
            if j < 0:
                raise UnsupportedSql(f"unterminated quoted identifier at {i}")
            toks.append(Token("ident", sql[i + 1:j], i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = False
            seen_e = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_e:
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_e and j > i:
                    seen_e = True
                    j += 1
                    if j < n and sql[j] in "+-":
                        j += 1
                else:
                    break
            toks.append(Token("number", sql[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j]
            low = word.lower()
            if low in KEYWORDS:
                toks.append(Token("kw", low, i))
            else:
                toks.append(Token("ident", word, i))
            i = j
            continue
        two = sql[i:i + 2]
        if two in _TWO_CHAR:
            toks.append(Token("op", two, i))
            i += 2
            continue
        if c in _ONE_CHAR:
            toks.append(Token("op", c, i))
            i += 1
            continue
        raise UnsupportedSql(f"unexpected character {c!r} at {i}")
    toks.append(Token("eof", "", n))
    return toks
